"""Arithmetic-intensity cost model: scores candidates without a chip.

Off-chip (CPU CI) the autotuner cannot time kernels, but it can still
rank them: each candidate's runtime is modeled as the roofline max of
compute time and memory time plus a per-grid-program launch overhead,
with a VMEM-working-set feasibility gate.  The constants are a generic
TPU-class device — absolute numbers are meaningless, the RANKING is
what the sweep persists, and on-chip wall-clock measurement replaces
this model entirely (``--wall`` mode).
"""
from __future__ import annotations

import math

__all__ = ["estimate", "PEAK_FLOPS", "PEAK_BW", "VMEM_BYTES"]

PEAK_FLOPS = 200e12     # flop/s, generic bf16-class systolic peak
PEAK_BW = 1.0e12        # byte/s HBM
VMEM_BYTES = 64 << 20   # per-core VMEM working-set budget
PER_PROGRAM_S = 1.2e-6  # grid-program launch/prologue overhead
PER_TILE_S = 0.1e-6     # per inner-tile loop overhead (k-blocks, pages)

_DTYPE_BYTES = {"float32": 4, "bfloat16": 2, "float16": 2, "int8": 1}


def _bytes(dtype: str) -> int:
    return _DTYPE_BYTES.get(dtype, 4)


def _roofline(flops: float, traffic: float, programs: float,
              tiles: float, vmem: float):
    if vmem > VMEM_BYTES:
        return math.inf
    return (max(flops / PEAK_FLOPS, traffic / PEAK_BW)
            + programs * PER_PROGRAM_S + tiles * PER_TILE_S)


def _flash(shape: dict, config: dict) -> float:
    sq, sk, d = shape["seq_q"], shape["seq_k"], shape["head_dim"]
    eb = _bytes(shape.get("dtype", "float32"))
    bq = min(config["block_q"], sq)
    bk = min(config["block_k"], sk)
    heads = shape.get("heads", 8)
    programs = heads * math.ceil(sq / bq)
    tiles = programs * math.ceil(sk / bk)
    flops = 4.0 * heads * sq * sk * d
    # each q-block streams the full K/V once; bigger q-blocks mean fewer
    # K/V passes, bigger k-blocks amortize tile overhead
    traffic = eb * heads * (sq * d * 2 + math.ceil(sq / bq) * sk * d * 2)
    vmem = eb * (bq * d + 2 * bk * d) + 4 * bq * d + 4 * bq * 2
    return _roofline(flops, traffic, programs, tiles, vmem)


def _norms(shape: dict, config: dict) -> float:
    rows, hidden = shape["rows"], shape["hidden"]
    eb = _bytes(shape.get("dtype", "float32"))
    br = min(config["block_r"], rows)
    programs = math.ceil(rows / br)
    flops = 8.0 * rows * hidden
    traffic = eb * rows * hidden * 2
    vmem = eb * br * hidden * 2 + 4 * br * hidden
    return _roofline(flops, traffic, programs, programs, vmem)


def _paged(shape: dict, config: dict) -> float:
    tq, kvh, d = shape["tq"], shape["kv_heads"], shape["head_dim"]
    page, nblk = shape["page"], shape["nblk"]
    eb = _bytes(shape.get("dtype", "float32"))
    p = max(1, config["pages_per_step"])
    steps = math.ceil(nblk / p)
    programs = tq * kvh * steps
    flops = 4.0 * tq * kvh * nblk * page * d
    traffic = eb * tq * kvh * nblk * page * d * 2 + 4.0 * tq * kvh * d
    # p page-pairs resident per step plus the f32 accumulator
    vmem = eb * p * page * d * 2 + 4 * d * 3
    return _roofline(flops, traffic, programs, programs * p, vmem)


_MODELS = {
    "flash_attention": _flash,
    "flash_attention_varlen": _flash,
    "fused_norms": _norms,
    "paged_attention": _paged,
}


def estimate(kernel: str, shape: dict, config: dict) -> float:
    """Modeled seconds for one launch; math.inf when infeasible."""
    fn = _MODELS.get(kernel)
    if fn is None:
        raise KeyError(f"no cost model for kernel {kernel!r}")
    return fn(shape, config)
