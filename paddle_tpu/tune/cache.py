"""Persistent kernel-tuning cache + the trace-time lookup helper.

The cache is one JSON file of winners keyed by
``device kind | kernel name | shape-bucket signature``.  Kernels consult
it at TRACE time through :func:`kernel_config` — a pure host-side dict
read, so a lookup can never add a compile beyond the program budget the
caller already pays.  Resolution walks a fixed fallback chain:

1. forced config (``PADDLE_TPU_TUNE_FORCE`` — the sweep worker's lever);
2. deprecated env overrides registered for the kernel (e.g. the old
   ``PADDLE_TPU_FA_BLOCK_Q/K`` levers — honored, with a
   DeprecationWarning, so existing ablation scripts keep working);
3. exact cache key for this device + kernel + shape bucket;
4. nearest bucket for this device + kernel (numeric fields may differ,
   non-numeric fields — dtype — must match);
5. the kernel's built-in defaults.

A corrupt or missing cache file degrades to an empty cache (warn once):
tuning must never be able to take serving down.
"""
from __future__ import annotations

import json
import math
import os
import threading
import warnings

__all__ = [
    "TuningCache", "bucket_signature", "device_kind", "cache_path",
    "set_cache_path", "current_cache", "kernel_config",
    "kernel_config_with_meta", "provenance_snapshot", "reset_provenance",
]

_ENV_CACHE_PATH = "PADDLE_TPU_TUNE_CACHE"
_ENV_FORCE = "PADDLE_TPU_TUNE_FORCE"


def _default_path() -> str:
    return os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                        "tuning_cache.json")


def device_kind() -> str:
    """Canonical device key for cache entries ('cpu', 'tpu-v5-litepod'...).

    Imports jax lazily: the cache module itself must stay importable in
    contexts that never touch a backend (the lint CLI, doc tooling)."""
    try:
        import jax
        kind = jax.devices()[0].device_kind
    except Exception:
        kind = "cpu"
    return str(kind).strip().lower().replace(" ", "-")


def _bucket(v):
    """Pow2 bucket for ints (shape dims); everything else verbatim."""
    if isinstance(v, bool) or not isinstance(v, int):
        return v
    if v <= 1:
        return v
    return 1 << (v - 1).bit_length()


def bucket_signature(shape_key: dict) -> str:
    """Canonical bucketed signature: sorted ``field=value`` pairs with
    integer fields rounded up to a power of two, so near-identical shapes
    share one tuning entry instead of fragmenting the cache."""
    parts = []
    for k in sorted(shape_key):
        parts.append(f"{k}={_bucket(shape_key[k])}")
    return ",".join(parts)


def _parse_sig(sig: str) -> dict:
    out = {}
    for part in sig.split(","):
        if "=" not in part:
            continue
        k, v = part.split("=", 1)
        try:
            out[k] = int(v)
        except ValueError:
            out[k] = v
    return out


def _sig_distance(a: str, b: str):
    """Bucket distance between two signatures, or None when incomparable
    (different field sets or mismatched non-numeric fields)."""
    da, db = _parse_sig(a), _parse_sig(b)
    if set(da) != set(db):
        return None
    dist = 0.0
    for k, va in da.items():
        vb = db[k]
        if isinstance(va, int) and isinstance(vb, int):
            dist += abs(math.log2(va + 1) - math.log2(vb + 1))
        elif va != vb:
            return None
    return dist


class TuningCache:
    """One JSON file of tuning winners; loads lazily, saves atomically."""

    VERSION = 1

    def __init__(self, path: str | None = None):
        self.path = path or _default_path()
        self._entries: dict = {}
        self._loaded = False
        self._load_error: str | None = None
        self._lock = threading.Lock()

    # -- persistence --------------------------------------------------------

    def _ensure_loaded(self) -> None:
        if self._loaded:
            return
        with self._lock:
            if self._loaded:
                return
            self._entries = {}
            if os.path.exists(self.path):
                try:
                    doc = json.load(open(self.path))
                    entries = doc["entries"]
                    if not isinstance(entries, dict):
                        raise TypeError("entries must be a dict")
                    for key, rec in entries.items():
                        if isinstance(rec, dict) and \
                                isinstance(rec.get("config"), dict):
                            self._entries[str(key)] = rec
                except Exception as e:
                    # corrupt cache == empty cache: every lookup falls
                    # back to defaults rather than crashing a trace
                    self._load_error = f"{type(e).__name__}: {e}"
                    warnings.warn(
                        f"tuning cache {self.path!r} is unreadable "
                        f"({self._load_error}); using built-in defaults",
                        RuntimeWarning, stacklevel=3)
            self._loaded = True

    def save(self, path: str | None = None) -> str:
        """Atomic write (tmp + os.replace): a mid-write crash must not
        truncate a cache other processes consult."""
        self._ensure_loaded()
        path = path or self.path
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        doc = {"version": self.VERSION, "entries": self._entries}
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        return path

    # -- entries ------------------------------------------------------------

    @staticmethod
    def key(device: str, kernel: str, sig: str) -> str:
        return f"{device}|{kernel}|{sig}"

    def put(self, device: str, kernel: str, sig: str, config: dict, *,
            score_s: float | None = None, measure: str = "") -> None:
        self._ensure_loaded()
        rec = {"config": dict(config)}
        if score_s is not None:
            rec["score_s"] = float(score_s)
        if measure:
            rec["measure"] = measure
        self._entries[self.key(device, kernel, sig)] = rec

    def lookup(self, device: str, kernel: str, sig: str):
        """Exact entry for this (device, kernel, bucket) or None."""
        self._ensure_loaded()
        rec = self._entries.get(self.key(device, kernel, sig))
        return dict(rec["config"]) if rec else None

    def nearest(self, device: str, kernel: str, sig: str):
        """Closest same-device same-kernel bucket: (config, sig) or None.
        Numeric fields compare by log2 distance; non-numeric fields
        (dtype) must match exactly — a bf16 winner never configures an
        f32 launch."""
        self._ensure_loaded()
        prefix = f"{device}|{kernel}|"
        best = None
        for key, rec in self._entries.items():
            if not key.startswith(prefix):
                continue
            cand_sig = key[len(prefix):]
            d = _sig_distance(sig, cand_sig)
            if d is None:
                continue
            if best is None or (d, cand_sig) < (best[0], best[2]):
                best = (d, dict(rec["config"]), cand_sig)
        if best is None:
            return None
        return best[1], best[2]

    def kernels(self, device: str | None = None) -> set:
        """Kernel names with at least one entry (optionally per device)."""
        self._ensure_loaded()
        out = set()
        for key in self._entries:
            dev, kern, _ = key.split("|", 2)
            if device is None or dev == device:
                out.add(kern)
        return out

    def __len__(self) -> int:
        self._ensure_loaded()
        return len(self._entries)


# ---------------------------------------------------------------------------
# process-wide cache singleton + provenance ledger
# ---------------------------------------------------------------------------

_EXPLICIT_PATH: str | None = None
_CACHE: TuningCache | None = None
_CACHE_LOCK = threading.Lock()

# kernel -> {"hits", "misses", "source", "config", "key"}; serve_bench,
# mfu_ablation, and LLMEngine.summary() all render snapshots of this
_PROVENANCE: dict = {}

# deprecated env vars already warned about (tests clear this to re-arm)
_ENV_WARNED: set = set()


def cache_path() -> str:
    """Resolved cache path: explicit set_cache_path() wins, then the
    PADDLE_TPU_TUNE_CACHE env var, then the per-user default."""
    if _EXPLICIT_PATH is not None:
        return _EXPLICIT_PATH
    return os.environ.get(_ENV_CACHE_PATH) or _default_path()


def set_cache_path(path: str | None) -> None:
    """Point the process at a different tuning cache (None = back to the
    env/default resolution).  Resets the loaded singleton so the next
    lookup reads the new file."""
    global _EXPLICIT_PATH, _CACHE
    with _CACHE_LOCK:
        _EXPLICIT_PATH = path
        _CACHE = None


def current_cache() -> TuningCache:
    """The process-wide cache for the currently-resolved path.  A path
    change (set_cache_path or env var) swaps in a fresh instance."""
    global _CACHE
    path = cache_path()
    with _CACHE_LOCK:
        if _CACHE is None or _CACHE.path != path:
            _CACHE = TuningCache(path)
        return _CACHE


def reset_provenance() -> None:
    _PROVENANCE.clear()


def provenance_snapshot() -> dict:
    """Copy of the process-wide lookup ledger: which cache was consulted
    and, per kernel, hit/miss counts plus the config last chosen."""
    return {
        "path": cache_path(),
        "device": device_kind(),
        "kernels": {k: dict(v) for k, v in _PROVENANCE.items()},
    }


def _record(kernel: str, source: str, config: dict, sig: str) -> None:
    slot = _PROVENANCE.setdefault(
        kernel, {"hits": 0, "misses": 0, "source": "", "config": {},
                 "key": ""})
    if source in ("exact", "bucket"):
        slot["hits"] += 1
    else:
        slot["misses"] += 1
    slot["source"] = source
    slot["config"] = dict(config)
    slot["key"] = sig


def _forced_config(kernel: str):
    raw = os.environ.get(_ENV_FORCE)
    if not raw:
        return None
    try:
        doc = json.loads(raw)
        cfg = doc.get(kernel)
        return dict(cfg) if isinstance(cfg, dict) else None
    except Exception:
        return None


def _env_overrides(kernel: str) -> dict:
    """Deprecated per-kernel env levers (registry-declared).  Still win
    over the cache so existing sweep scripts keep steering geometry, but
    each variable warns once per process."""
    from .registry import get_kernel
    reg = get_kernel(kernel)
    if reg is None or not reg.env_overrides:
        return {}
    out = {}
    for param, var in reg.env_overrides.items():
        raw = os.environ.get(var)
        if raw is None:
            continue
        try:
            out[param] = int(raw)
        except ValueError:
            continue
        if var not in _ENV_WARNED:
            _ENV_WARNED.add(var)
            warnings.warn(
                f"{var} is deprecated; write a tuning-cache entry instead "
                "(tools/perf/autotune.py) or set PADDLE_TPU_TUNE_FORCE",
                DeprecationWarning, stacklevel=4)
    return out


def kernel_config_with_meta(name: str, shape_key: dict,
                            defaults: dict | None = None):
    """Resolve a kernel's launch geometry and say where it came from.

    Returns ``(config, meta)`` where meta carries ``source`` (forced /
    env / exact / bucket / default), ``hit`` (source was a cache entry),
    ``key`` (the bucket signature queried) and ``matched`` (the entry's
    signature when a bucket fallback answered).
    """
    from .registry import get_kernel
    reg = get_kernel(name)
    base = dict(reg.defaults) if reg is not None else {}
    if defaults:
        base.update(defaults)
    sig = bucket_signature(shape_key)
    dev = device_kind()

    forced = _forced_config(name)
    env = _env_overrides(name)
    source, matched = "default", sig
    config = dict(base)
    if forced is not None:
        config.update(forced)
        source = "forced"
    else:
        cache = current_cache()
        found = cache.lookup(dev, name, sig)
        if found is not None:
            config.update(found)
            source = "exact"
        else:
            near = cache.nearest(dev, name, sig)
            if near is not None:
                config.update(near[0])
                source, matched = "bucket", near[1]
        if env:
            config.update(env)
            source = "env"
    meta = {"source": source, "hit": source in ("exact", "bucket"),
            "key": sig, "matched": matched, "device": dev}
    _record(name, source, config, sig)
    return config, meta


def kernel_config(name: str, shape_key: dict,
                  defaults: dict | None = None) -> dict:
    """THE trace-time lookup helper every Pallas launch's geometry must
    flow from (graft-lint rule ``untuned-pallas-launch`` enforces this
    for ops/pallas).  Pure host-side dict read — adds no compile."""
    config, _ = kernel_config_with_meta(name, shape_key, defaults)
    return config
