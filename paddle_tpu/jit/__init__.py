"""jit: captured/compiled execution.

The reference's to_static (/root/reference/python/paddle/jit/api.py:197)
captures python into a static PIR program via SOT bytecode tracing, compiles
with CINN, and caches on input guards
(/root/reference/python/paddle/jit/sot/symbolic/compile_cache.py).  On TPU the
capture mechanism is JAX tracing: run the eager Tensor machinery under
jax.jit; the guard cache is jit's (shape, dtype) signature cache.  This is
where TPU perf comes from — the whole forward (or train step) becomes one
fused XLA program.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..core import dispatch
from ..core.tensor import Parameter, Tensor
from ..nn.layer.layers import Layer

__all__ = ["to_static", "save", "load", "ignore_module", "not_to_static",
           "TracedFunction", "TranslatedLayer", "InputSpec",
           "set_code_level", "set_verbosity", "enable_to_static",
           "capture_step", "CapturedStep"]

_to_static_enabled = True


def _tree_to_arrays(obj):
    if isinstance(obj, Tensor):
        return obj._data
    if isinstance(obj, (list, tuple)):
        return type(obj)(_tree_to_arrays(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _tree_to_arrays(v) for k, v in obj.items()}
    return obj


def _tree_to_tensors(obj, stop_gradient=True):
    if isinstance(obj, jax.Array):
        return Tensor(obj, stop_gradient=stop_gradient)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_tree_to_tensors(o, stop_gradient) for o in obj)
    if isinstance(obj, dict):
        return {k: _tree_to_tensors(v, stop_gradient) for k, v in obj.items()}
    return obj


class TracedFunction:
    """A function (or Layer.forward) compiled as one XLA program.

    Parameters/buffers are threaded as explicit inputs so the cache stays
    valid across optimizer updates (reference analog: partial_program's
    parameter feeding).
    """

    def __init__(self, fn, layer=None, input_spec=None, full_graph=True):
        self._fn = fn
        self._layer = layer
        self._input_spec = input_spec
        self._compiled = None

    def _build(self):
        layer = self._layer
        fn = self._fn

        if layer is not None:
            named_params = dict(layer.named_parameters())
            named_buffers = dict(layer.named_buffers())

            def pure(param_arrays, buffer_arrays, args, kwargs):
                # bind arrays into the live layer, run, restore
                saved_p = {k: p._data for k, p in named_params.items()}
                saved_b = {k: b._data for k, b in named_buffers.items()}
                try:
                    for k, p in named_params.items():
                        p._data = param_arrays[k]
                    for k, b in named_buffers.items():
                        b._data = buffer_arrays[k]
                    t_args = _tree_to_tensors(args)
                    t_kwargs = _tree_to_tensors(kwargs)
                    with dispatch.no_grad():
                        out = fn(*t_args, **t_kwargs)
                    return _tree_to_arrays(out)
                finally:
                    for k, p in named_params.items():
                        p._data = saved_p[k]
                    for k, b in named_buffers.items():
                        b._data = saved_b[k]

            self._compiled = jax.jit(pure)
        else:
            def pure(args, kwargs):
                t_args = _tree_to_tensors(args)
                t_kwargs = _tree_to_tensors(kwargs)
                with dispatch.no_grad():
                    out = fn(*t_args, **t_kwargs)
                return _tree_to_arrays(out)
            self._compiled = jax.jit(pure)

    def __call__(self, *args, **kwargs):
        if not _to_static_enabled:
            # enable_to_static(False): run the original eagerly (reference
            # api.py enable_to_static contract)
            return self._fn(*args, **kwargs)
        if self._compiled is None:
            self._build()
        a = _tree_to_arrays(args)
        k = _tree_to_arrays(kwargs)
        if self._layer is not None:
            params = {k2: p._data for k2, p in self._layer.named_parameters()}
            buffers = {k2: b._data for k2, b in self._layer.named_buffers()}
            out = self._compiled(params, buffers, a, k)
        else:
            out = self._compiled(a, k)
        return _tree_to_tensors(out)

    @property
    def forward(self):
        return self


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph=True, **kwargs):
    """Compile a function or Layer into a cached XLA program."""

    def decorate(obj):
        if isinstance(obj, Layer):
            traced = TracedFunction(obj.forward, layer=obj, input_spec=input_spec)
            obj.forward = traced
            return obj
        if callable(obj):
            layer = getattr(obj, "__self__", None)
            layer = layer if isinstance(layer, Layer) else None
            return TracedFunction(obj, layer=layer, input_spec=input_spec)
        raise TypeError(f"to_static expects a Layer or callable, got {type(obj)}")

    if function is None:
        return decorate
    return decorate(function)


def not_to_static(fn=None):
    return fn


def ignore_module(modules):
    return None


class InputSpec:
    """Input signature element (reference paddle.static.InputSpec)."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = tuple(int(s) if s is not None else None for s in shape)
        self.dtype = dtype
        self.name = name

    def _struct(self):
        from ..core.dtype import convert_dtype
        if any(s is None for s in self.shape):
            raise ValueError(
                "dynamic dims are not supported in jit.save; give concrete "
                f"shapes (got {self.shape})")
        return jax.ShapeDtypeStruct(self.shape,
                                    convert_dtype(self.dtype).np_dtype)

    @classmethod
    def from_tensor(cls, t, name=None):
        return cls(tuple(t.shape), t.dtype.name, name)


def _spec_struct(spec):
    if isinstance(spec, InputSpec):
        return spec._struct()
    if isinstance(spec, Tensor):
        return jax.ShapeDtypeStruct(tuple(spec.shape), spec._data.dtype)
    if isinstance(spec, jax.Array):
        return jax.ShapeDtypeStruct(spec.shape, spec.dtype)
    raise TypeError(f"input_spec entries must be InputSpec/Tensor, got "
                    f"{type(spec)}")


def save(layer, path, input_spec=None, **configs):
    """Serialize a Layer/function as a deployable program:
    <path>.pdmodel   — the StableHLO program (jax.export serialization;
                       the TPU analog of the reference's translated static
                       program, jit/api.py save)
    <path>.pdiparams — parameters + buffers (npz)
    jit.load(path) restores a TranslatedLayer that executes the saved
    program without the original python code.
    """
    import numpy as np
    from jax import export as jax_export

    if input_spec is None:
        raise ValueError("jit.save requires input_spec (a list of "
                         "InputSpec or example Tensors)")
    fn = layer.forward if isinstance(layer, Layer) else layer
    if isinstance(fn, TracedFunction):
        fn = fn._fn
    named_params = (dict(layer.named_parameters())
                    if isinstance(layer, Layer) else {})
    named_buffers = (dict(layer.named_buffers())
                     if isinstance(layer, Layer) else {})

    def pure(param_arrays, buffer_arrays, *in_arrays):
        saved_p = {k: p._data for k, p in named_params.items()}
        saved_b = {k: b._data for k, b in named_buffers.items()}
        try:
            for k, p in named_params.items():
                p._data = param_arrays[k]
            for k, b in named_buffers.items():
                b._data = buffer_arrays[k]
            t_args = _tree_to_tensors(in_arrays)
            with dispatch.no_grad():
                out = fn(*t_args)
            return _tree_to_arrays(out)
        finally:
            for k, p in named_params.items():
                p._data = saved_p[k]
            for k, b in named_buffers.items():
                b._data = saved_b[k]

    p_structs = {k: jax.ShapeDtypeStruct(p._data.shape, p._data.dtype)
                 for k, p in named_params.items()}
    b_structs = {k: jax.ShapeDtypeStruct(b._data.shape, b._data.dtype)
                 for k, b in named_buffers.items()}
    in_structs = [_spec_struct(s) for s in input_spec]
    exported = jax_export.export(jax.jit(pure))(p_structs, b_structs,
                                                *in_structs)
    with open(path + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    save_params_npz(path,
                    {k: p._data for k, p in named_params.items()},
                    {k: b._data for k, b in named_buffers.items()})


def save_params_npz(prefix, params, buffers):
    """Write the <prefix>.pdiparams.npz artifact (jit.load's counterpart).

    ml_dtypes arrays (bf16 etc.) cannot be represented in npz natively —
    they are stored as integer bit patterns plus a ``meta::dtypes``
    manifest that load() uses to view them back.
    """
    import json

    import numpy as np
    payload, manifest = {}, {}
    for kind, items in (("param", params), ("buffer", buffers)):
        for k, v in items.items():
            key = f"{kind}::{k}"
            a = np.asarray(v)
            if a.dtype.kind == "V":
                manifest[key] = str(v.dtype)
                a = a.view(np.uint16 if a.dtype.itemsize == 2 else np.uint8)
            payload[key] = a
    if manifest:
        payload["meta::dtypes"] = np.asarray(json.dumps(manifest))
    np.savez(prefix + ".pdiparams", **payload)


class TranslatedLayer(Layer):
    """A loaded serialized program (reference jit/translated_layer.py):
    parameters are real Parameters (trainable state_dict), forward executes
    the deserialized StableHLO program."""

    def __init__(self, exported, params, buffers):
        super().__init__()
        self._exported = exported
        self._loaded_params = {}
        for k, arr in params.items():
            p = Parameter(jnp.asarray(arr))
            self._loaded_params[k] = p
            # register flat under the ORIGINAL dotted name so state_dict
            # keys match the source model's (set_state_dict round-trips)
            self._parameters[k] = p
        self._loaded_buffers = {k: jnp.asarray(v) for k, v in buffers.items()}

    def forward(self, *inputs):
        arrays = [i._data if isinstance(i, Tensor) else jnp.asarray(i)
                  for i in inputs]
        p = {k: t._data for k, t in self._loaded_params.items()}
        out = self._exported.call(p, self._loaded_buffers, *arrays)
        return _tree_to_tensors(out)


def load(path, **configs):
    import json

    import ml_dtypes  # noqa: F401  (registers bfloat16 et al. with numpy)
    import numpy as np
    from jax import export as jax_export
    with open(path + ".pdmodel", "rb") as f:
        exported = jax_export.deserialize(f.read())
    params, buffers = {}, {}
    dtypes = {}
    with np.load(path + ".pdiparams.npz") as z:
        if "meta::dtypes" in z.files:
            # npz can't represent ml_dtypes (bf16 saves as raw V2): such
            # arrays are stored as uint16 bit patterns plus this manifest
            dtypes = json.loads(str(z["meta::dtypes"]))
        for key in z.files:
            if key == "meta::dtypes":
                continue
            kind, name = key.split("::", 1)
            arr = z[key]
            if key in dtypes:
                arr = arr.view(np.dtype(dtypes[key]))
            (params if kind == "param" else buffers)[name] = arr
    return TranslatedLayer(exported, params, buffers)


def set_code_level(level=100, also_to_stdout=False):
    """Dy2static transformed-code logging (reference jit/api set_code_level).
    This build traces via JAX rather than AST-transforming source, so the
    knob maps to the capture-path log level."""
    import logging
    import sys
    log = logging.getLogger("paddle_tpu.jit")
    log.setLevel(logging.DEBUG if level > 0 else logging.WARNING)
    if also_to_stdout and not any(
            getattr(h, "stream", None) is sys.stdout for h in log.handlers):
        log.addHandler(logging.StreamHandler(sys.stdout))


def set_verbosity(level=0, also_to_stdout=False):
    """(reference jit/api set_verbosity — same logger as set_code_level)"""
    set_code_level(level, also_to_stdout)


def enable_to_static(enable=True):
    """Globally toggle to_static capture (reference api.py enable_to_static):
    when off, to_static-wrapped callables run eagerly."""
    global _to_static_enabled
    _to_static_enabled = bool(enable)


from .step import CapturedStep, capture_step  # noqa: E402
