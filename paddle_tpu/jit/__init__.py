"""jit: captured/compiled execution.

The reference's to_static (/root/reference/python/paddle/jit/api.py:197)
captures python into a static PIR program via SOT bytecode tracing, compiles
with CINN, and caches on input guards
(/root/reference/python/paddle/jit/sot/symbolic/compile_cache.py).  On TPU the
capture mechanism is JAX tracing: run the eager Tensor machinery under
jax.jit; the guard cache is jit's (shape, dtype) signature cache.  This is
where TPU perf comes from — the whole forward (or train step) becomes one
fused XLA program.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..core import dispatch
from ..core.tensor import Parameter, Tensor
from ..nn.layer.layers import Layer

__all__ = ["to_static", "save", "load", "ignore_module", "not_to_static",
           "TracedFunction"]


def _tree_to_arrays(obj):
    if isinstance(obj, Tensor):
        return obj._data
    if isinstance(obj, (list, tuple)):
        return type(obj)(_tree_to_arrays(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _tree_to_arrays(v) for k, v in obj.items()}
    return obj


def _tree_to_tensors(obj, stop_gradient=True):
    if isinstance(obj, jax.Array):
        return Tensor(obj, stop_gradient=stop_gradient)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_tree_to_tensors(o, stop_gradient) for o in obj)
    if isinstance(obj, dict):
        return {k: _tree_to_tensors(v, stop_gradient) for k, v in obj.items()}
    return obj


class TracedFunction:
    """A function (or Layer.forward) compiled as one XLA program.

    Parameters/buffers are threaded as explicit inputs so the cache stays
    valid across optimizer updates (reference analog: partial_program's
    parameter feeding).
    """

    def __init__(self, fn, layer=None, input_spec=None, full_graph=True):
        self._fn = fn
        self._layer = layer
        self._input_spec = input_spec
        self._compiled = None

    def _build(self):
        layer = self._layer
        fn = self._fn

        if layer is not None:
            named_params = dict(layer.named_parameters())
            named_buffers = dict(layer.named_buffers())

            def pure(param_arrays, buffer_arrays, args, kwargs):
                # bind arrays into the live layer, run, restore
                saved_p = {k: p._data for k, p in named_params.items()}
                saved_b = {k: b._data for k, b in named_buffers.items()}
                try:
                    for k, p in named_params.items():
                        p._data = param_arrays[k]
                    for k, b in named_buffers.items():
                        b._data = buffer_arrays[k]
                    t_args = _tree_to_tensors(args)
                    t_kwargs = _tree_to_tensors(kwargs)
                    with dispatch.no_grad():
                        out = fn(*t_args, **t_kwargs)
                    return _tree_to_arrays(out)
                finally:
                    for k, p in named_params.items():
                        p._data = saved_p[k]
                    for k, b in named_buffers.items():
                        b._data = saved_b[k]

            self._compiled = jax.jit(pure)
        else:
            def pure(args, kwargs):
                t_args = _tree_to_tensors(args)
                t_kwargs = _tree_to_tensors(kwargs)
                with dispatch.no_grad():
                    out = fn(*t_args, **t_kwargs)
                return _tree_to_arrays(out)
            self._compiled = jax.jit(pure)

    def __call__(self, *args, **kwargs):
        if self._compiled is None:
            self._build()
        a = _tree_to_arrays(args)
        k = _tree_to_arrays(kwargs)
        if self._layer is not None:
            params = {k2: p._data for k2, p in self._layer.named_parameters()}
            buffers = {k2: b._data for k2, b in self._layer.named_buffers()}
            out = self._compiled(params, buffers, a, k)
        else:
            out = self._compiled(a, k)
        return _tree_to_tensors(out)

    @property
    def forward(self):
        return self


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph=True, **kwargs):
    """Compile a function or Layer into a cached XLA program."""

    def decorate(obj):
        if isinstance(obj, Layer):
            traced = TracedFunction(obj.forward, layer=obj, input_spec=input_spec)
            obj.forward = traced
            return obj
        if callable(obj):
            layer = getattr(obj, "__self__", None)
            layer = layer if isinstance(layer, Layer) else None
            return TracedFunction(obj, layer=layer, input_spec=input_spec)
        raise TypeError(f"to_static expects a Layer or callable, got {type(obj)}")

    if function is None:
        return decorate
    return decorate(function)


def not_to_static(fn=None):
    return fn


def ignore_module(modules):
    return None


def save(layer, path, input_spec=None, **configs):
    """jit.save analog: persist params + (optionally) the traced signature.

    StableHLO program export lands with the inference-deploy milestone; the
    state_dict payload round-trips through paddle_tpu.load today.
    """
    from ..framework.io import save as _save
    state = layer.state_dict() if isinstance(layer, Layer) else {}
    _save({"state_dict": state, "class": type(layer).__name__}, path + ".pdparams")


def load(path, **configs):
    from ..framework.io import load as _load
    return _load(path + ".pdparams")
