"""Whole-train-step capture: the TPU answer to eager dispatch overhead.

The reference keeps its dygraph hot loop fast with a C++ dispatch chain
(/root/reference/paddle/fluid/pybind/eager_method.cc, eager_gen.py); on TPU
no per-op dispatcher can win — every launch is a device round-trip, and over
a remote PJRT link each one costs milliseconds.  The TPU-native fix is to
compile the USER'S OWN dygraph step — forward, tape backward, GradScaler,
optimizer update — into ONE XLA program (the same shape as the reference's
dygraph-to-static SOT capture, /root/reference/python/paddle/jit/api.py:197,
but with jax tracing as the capture mechanism).

    step = paddle.jit.capture_step(train_step, models=model,
                                   optimizers=opt, scalers=scaler)
    for batch in loader:
        loss = step(batch_x, batch_y)      # one fused XLA launch

Mutable framework state — parameters (+ AMP master weights), buffers,
optimizer accumulators, GradScaler scale schedule, global RNG stream — is
threaded through the compiled program as explicit donated inputs/outputs, so
repeated calls reuse buffers and never sync the host.  Dynamic scalars that
must not bake into the trace (learning rate, Adam bias-correction step,
loss-scale) ride as inputs; LR schedulers therefore keep working when
stepped BETWEEN captured calls.

Contract (enforced with clear errors):
- the step function must not materialize tensors (``.numpy()``, ``float()``,
  ``if tensor:``) — that is a host sync inside the compiled program;
- gradients must be cleared inside the step (``opt.clear_grad()``) —
  unless ``grad_accumulation=True``, which threads gradients through the
  program so an accumulate-only fn and an update fn (two captured steps
  over the same objects) implement the every-k pattern;
- optimizers whose update depends on host-side per-step state (NAdam's
  mu-product, RAdam's rho branch) are rejected; the Adam/AdamW family,
  SGD, Momentum, Adamax, Lamb and ASGD are supported.
"""
from __future__ import annotations

import warnings

import jax
import jax.numpy as jnp

from ..core import random_state
from ..core.tensor import Tensor

__all__ = ["capture_step", "CapturedStep"]


def _as_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


class CapturedStep:
    """A user train-step function compiled as one XLA program."""

    def __init__(self, fn, models=None, optimizers=None, scalers=None,
                 donate=True, grad_accumulation=False):
        self._fn = fn
        self._models = _as_list(models)
        self._optimizers = _as_list(optimizers)
        self._scalers = _as_list(scalers)
        self._donate = donate
        self._grad_accum = bool(grad_accumulation)
        self._compiled = None
        self._rng_draws = 0

        # ---- stable state inventory (built once) ----
        seen = set()
        self._params = []          # Parameter objects, stable order
        for m in self._models:
            for _, p in m.named_parameters():
                if id(p) not in seen:
                    seen.add(id(p))
                    self._params.append(p)
        for opt in self._optimizers:
            for p in (opt._parameter_list or []):
                if id(p) not in seen:
                    seen.add(id(p))
                    self._params.append(p)
        self._buffers = []
        bseen = set()
        for m in self._models:
            for _, b in m.named_buffers():
                if b is not None and id(b) not in bseen:
                    bseen.add(id(b))
                    self._buffers.append(b)
        # pre-create every optimizer slot so the state signature is stable
        # from the first call (lazily-created slots would change the pytree
        # structure between call 1 and call 2 and force a retrace)
        for opt in self._optimizers:
            for p in (opt._parameter_list or []):
                if not p.stop_gradient:
                    opt._state_for(p)
        self._slot_index = []      # (opt_i, param_obj, slot_name)
        for oi, opt in enumerate(self._optimizers):
            names = tuple(opt._slot_names())
            for p in (opt._parameter_list or []):
                st = opt._accumulators.get(id(p))
                if st is None:
                    continue
                for n in names:
                    self._slot_index.append((oi, p, n))

    # -- state gather/scatter ------------------------------------------------
    def _gather_state(self):
        import jax.numpy as jnp
        donated = {
            "params": [p._data for p in self._params],
            # grad-accumulation mode threads gradients through the program
            # (zeros when cleared) so `backward(); every k: step()` splits
            # into two captured fns sharing the same accumulated state
            "grads": [] if not self._grad_accum else [
                p._grad._data if p._grad is not None
                else jnp.zeros_like(p._data) for p in self._params],
            "masters": [p._master_weight for p in self._params
                        if getattr(p, "_master_weight", None) is not None],
            "buffers": [b._data for b in self._buffers],
            "slots": [self._optimizers[oi]._accumulators[id(p)][n]
                      for oi, p, n in self._slot_index],
            "scalers": [list(s._capture_state()) for s in self._scalers],
        }
        key, counter = random_state.ensure_key()
        plain = {
            "rng_key": key,
            "rng_base": jnp.asarray(counter, jnp.int32),
            "lrs": [jnp.asarray(opt.get_lr(), jnp.float32)
                    for opt in self._optimizers],
            "step_ts": [jnp.asarray(opt._global_step + 1, jnp.int32)
                        for opt in self._optimizers],
        }
        return donated, plain

    def _bind(self, donated, plain):
        """Install state arrays into the live objects; return the saved
        originals so the trace leaves no tracer behind."""
        saved = {
            "params": [(p, p._data) for p in self._params],
            "masters": [(p, p._master_weight) for p in self._params
                        if getattr(p, "_master_weight", None) is not None],
            "buffers": [(b, b._data) for b in self._buffers],
            "slots": [(self._optimizers[oi]._accumulators[id(p)], n,
                       self._optimizers[oi]._accumulators[id(p)][n])
                      for oi, p, n in self._slot_index],
            "grads": [(p, p._grad) for p in self._params],
            # the traced opt.step() bumps the host counter as a trace-time
            # side effect; the wrapper owns the real per-call increment
            "steps": [opt._global_step for opt in self._optimizers],
        }
        for p, arr in zip(self._params, donated["params"]):
            p._data = arr
        if self._grad_accum:
            from ..core.tensor import Tensor
            for p, arr in zip(self._params, donated["grads"]):
                p._grad = Tensor(arr)
        mi = 0
        for p in self._params:
            if getattr(p, "_master_weight", None) is not None:
                p._master_weight = donated["masters"][mi]
                mi += 1
        for b, arr in zip(self._buffers, donated["buffers"]):
            b._data = arr
        for (oi, p, n), arr in zip(self._slot_index, donated["slots"]):
            self._optimizers[oi]._accumulators[id(p)][n] = arr
        for s, st in zip(self._scalers, donated["scalers"]):
            s._begin_capture(*st)
        for opt, lr, t in zip(self._optimizers, plain["lrs"],
                              plain["step_ts"]):
            opt._lr_override = lr
            opt._step_t_override = t
        random_state.begin_capture(plain["rng_key"], plain["rng_base"])
        return saved

    def _collect_new(self):
        import jax.numpy as jnp
        new = {
            "params": [p._data for p in self._params],
            "grads": [] if not self._grad_accum else [
                p._grad._data if p._grad is not None
                else jnp.zeros_like(p._data) for p in self._params],
            "masters": [p._master_weight for p in self._params
                        if getattr(p, "_master_weight", None) is not None],
            "buffers": [b._data for b in self._buffers],
            "slots": [self._optimizers[oi]._accumulators[id(p)][n]
                      for oi, p, n in self._slot_index],
            "scalers": [list(s._end_capture()) for s in self._scalers],
        }
        dirty = [] if self._grad_accum else [
            p.name for p in self._params if p._grad is not None]
        if dirty:
            raise RuntimeError(
                "capture_step: gradients still set after the step for "
                f"{dirty[:3]}{'...' if len(dirty) > 3 else ''} — call "
                "optimizer.clear_grad() inside the captured function "
                "— or pass grad_accumulation=True to capture_step to thread "
                "accumulated gradients through the program")
        # slots created mid-trace (a param unfrozen after construction)
        # would be trace-local tracers invisible to the state threading
        n_slots = sum(len(st) for opt in self._optimizers
                      for st in opt._accumulators.values())
        if n_slots != len(self._slot_index):
            raise RuntimeError(
                "capture_step: optimizer state changed during the step "
                "(a parameter was unfrozen after capture was built?) — "
                "rebuild the CapturedStep after changing stop_gradient")
        return new

    def _restore(self, saved):
        for p, arr in saved["params"]:
            p._data = arr
        for p, arr in saved["masters"]:
            p._master_weight = arr
        for b, arr in saved["buffers"]:
            b._data = arr
        for st, n, arr in saved["slots"]:
            st[n] = arr
        for p, g in saved["grads"]:
            p._grad = g
        for s in self._scalers:
            s._cap = None
            s._found_inf_t = None
        for opt, st in zip(self._optimizers, saved["steps"]):
            opt._lr_override = None
            opt._step_t_override = None
            opt._global_step = st
        self._rng_draws = random_state.end_capture()

    # -- compile -------------------------------------------------------------
    def _build(self):
        from . import _tree_to_arrays, _tree_to_tensors

        def pure(donated, plain, args, kwargs):
            saved = self._bind(donated, plain)
            try:
                t_args = _tree_to_tensors(args, stop_gradient=True)
                t_kwargs = _tree_to_tensors(kwargs, stop_gradient=True)
                out = self._fn(*t_args, **t_kwargs)
                new_state = self._collect_new()
                return _tree_to_arrays(out), new_state
            finally:
                self._restore(saved)

        self._pure = pure
        self._compiled = jax.jit(
            pure, donate_argnums=(0,) if self._donate else ())

    def program_spec(self, *args, large_bytes: int = 1 << 20, **kwargs):
        """This captured step as an analysis ProgramSpec.

        ``args``/``kwargs`` are one example batch (shapes only are used).
        The spec carries the UNjitted ``pure`` body plus the donation the
        wrapper declares, so ``analyze_program`` can audit the whole
        train step — params/master-weights/optimizer-slot donation, host
        callbacks, bf16 upcasts — without compiling or running it.
        """
        from ..analysis import ProgramSpec
        from . import _tree_to_arrays

        if self._compiled is None:
            self._build()
        donated, plain = self._gather_state()
        dt = donated["params"][0].dtype if donated["params"] else None
        declared = dt if dt is not None and \
            jnp.dtype(dt).name in ("bfloat16", "float16") else None
        return ProgramSpec(
            "jit.capture_step", self._pure,
            (donated, plain, _tree_to_arrays(args),
             _tree_to_arrays(kwargs)),
            donate_argnums=(0,) if self._donate else (),
            declared_dtype=declared, large_bytes=large_bytes)

    # -- call ----------------------------------------------------------------
    def __call__(self, *args, **kwargs):
        from . import _tree_to_arrays, _tree_to_tensors

        if self._compiled is None:
            self._build()
        donated, plain = self._gather_state()
        a_args = _tree_to_arrays(args)
        a_kwargs = _tree_to_arrays(kwargs)
        try:
            with warnings.catch_warnings():
                # inner per-op executables carry their own donation hints;
                # under the enclosing trace those are expected to be unused
                warnings.filterwarnings(
                    "ignore", message=".*[Dd]onat.*", category=UserWarning)
                out, new_state = self._compiled(donated, plain, a_args,
                                                a_kwargs)
        except jax.errors.ConcretizationTypeError as e:
            raise RuntimeError(
                "capture_step: the step function forced a host sync on a "
                "traced value (float()/bool()/.numpy()/if-on-tensor). Keep "
                "the step device-pure; read metrics from the returned "
                "tensors instead.") from e
        # host-side heartbeat: a stuck multichip program inside this step
        # surfaces as the watchdog's CRITICAL dump instead of a silent hang
        from ..distributed.watchdog import comm_task_manager, watch
        if comm_task_manager._timeout() > 0 and new_state["params"]:
            watch("jit.capture_step", (), new_state["params"][0])
        # write results back into the live objects
        for p, arr in zip(self._params, new_state["params"]):
            p._data = arr
        if self._grad_accum:
            from ..core.tensor import Tensor
            for p, arr in zip(self._params, new_state["grads"]):
                p._grad = Tensor(arr)
        mi = 0
        for p in self._params:
            if getattr(p, "_master_weight", None) is not None:
                p._master_weight = new_state["masters"][mi]
                mi += 1
        for b, arr in zip(self._buffers, new_state["buffers"]):
            b._data = arr
        for (oi, p, n), arr in zip(self._slot_index, new_state["slots"]):
            self._optimizers[oi]._accumulators[id(p)][n] = arr
        for s, st in zip(self._scalers, new_state["scalers"]):
            s._load_capture_state(*st)
        for opt in self._optimizers:
            opt._global_step += 1
        random_state.advance(self._rng_draws)
        return _tree_to_tensors(out, stop_gradient=True)


def capture_step(fn=None, *, models=None, optimizers=None, scalers=None,
                 donate=True, grad_accumulation=False):
    """Compile a dygraph train-step function into one XLA program.

    Decorator or direct form::

        step = capture_step(train_step, models=m, optimizers=o, scalers=s)

        @capture_step(models=m, optimizers=o)
        def train_step(x, y): ...
    """
    if fn is None:
        def deco(f):
            return CapturedStep(f, models, optimizers, scalers, donate,
                                grad_accumulation)
        return deco
    return CapturedStep(fn, models, optimizers, scalers, donate,
                        grad_accumulation)


# graft-lint import-time hook (PT_ANALYSIS=strict; 'off' is a flag read)
from ..analysis import enforce_import as _enforce_import  # noqa: E402

_enforce_import(__name__, __file__)
