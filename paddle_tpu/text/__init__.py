"""Text datasets.

Capability parity with /root/reference/python/paddle/text/ (datasets/
imdb.py, conll05.py, uci_housing.py, movielens.py, wmt14.py...).  The
reference downloads corpora at construction; this build is offline-first:
each dataset accepts ``data_file=`` for a local copy and otherwise
generates a deterministic synthetic corpus with the same schema (the same
policy as the vision datasets, paddle_tpu/vision/datasets.py).
"""
from __future__ import annotations

import numpy as np

from ..io import Dataset

__all__ = ["Imdb", "UCIHousing", "Conll05st", "Movielens", "Imikolov",
           "WMT14", "WMT16", "ViterbiDecoder", "viterbi_decode"]


class Imdb(Dataset):
    """Binary sentiment dataset: (token_ids [seq], label {0,1})
    (reference text/datasets/imdb.py)."""

    VOCAB = 5000

    def __init__(self, data_file=None, mode="train", cutoff=150):
        self.mode = mode
        if data_file is not None:
            import pickle
            with open(data_file, "rb") as f:
                self.docs, self.labels = pickle.load(f)
            return
        rng = np.random.RandomState(0 if mode == "train" else 1)
        n = 512 if mode == "train" else 128
        self.docs, self.labels = [], []
        for i in range(n):
            label = i % 2
            length = rng.randint(16, cutoff)
            # class-dependent token distribution so models can learn
            lo, hi = (0, self.VOCAB // 2) if label == 0 \
                else (self.VOCAB // 2, self.VOCAB)
            self.docs.append(rng.randint(lo, hi, (length,)).astype(np.int64))
            self.labels.append(np.int64(label))

    def word_idx(self):
        return {f"w{i}": i for i in range(self.VOCAB)}

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class UCIHousing(Dataset):
    """13-feature housing regression (reference uci_housing.py)."""

    def __init__(self, data_file=None, mode="train"):
        if data_file is not None:
            data = np.loadtxt(data_file)
        else:
            rng = np.random.RandomState(7)
            n = 404 if mode == "train" else 102
            x = rng.randn(n, 13).astype(np.float32)
            w = rng.randn(13, 1).astype(np.float32)
            y = x @ w + 0.1 * rng.randn(n, 1).astype(np.float32)
            data = np.concatenate([x, y], axis=1)
        self.data = data.astype(np.float32)

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1], row[-1:]

    def __len__(self):
        return len(self.data)


class Conll05st(Dataset):
    """SRL dataset: (word_ids, predicate, ..., label_ids)
    (reference conll05.py schema: 8 input slots + labels)."""

    WORD_DICT = 2000
    PRED_DICT = 100
    LABEL_DICT = 67

    def __init__(self, data_file=None, mode="train"):
        if data_file is not None:
            raise NotImplementedError(
                "Conll05st serves synthetic SRL-schema data only (zero-egress"
                " build); loading a real corpus from data_file is not"
                " implemented — pass data_file=None.")
        rng = np.random.RandomState(3 if mode == "train" else 4)
        n = 256 if mode == "train" else 64
        self.samples = []
        for _ in range(n):
            length = rng.randint(5, 30)
            words = rng.randint(0, self.WORD_DICT, (length,)).astype(np.int64)
            pred = rng.randint(0, self.PRED_DICT, (length,)).astype(np.int64)
            labels = rng.randint(0, self.LABEL_DICT,
                                 (length,)).astype(np.int64)
            ctx = [rng.randint(0, self.WORD_DICT, (length,)).astype(np.int64)
                   for _ in range(5)]
            mark = rng.randint(0, 2, (length,)).astype(np.int64)
            self.samples.append(tuple([words] + ctx + [pred, mark, labels]))

    def get_dict(self):
        return ({f"w{i}": i for i in range(self.WORD_DICT)},
                {f"p{i}": i for i in range(self.PRED_DICT)},
                {f"l{i}": i for i in range(self.LABEL_DICT)})

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class Movielens(Dataset):
    """Rating prediction: (user feats, movie feats, rating)
    (reference movielens.py)."""

    def __init__(self, data_file=None, mode="train"):
        if data_file is not None:
            raise NotImplementedError(
                "Movielens serves synthetic schema-shaped data only"
                " (zero-egress build); loading the real dataset from"
                " data_file is not implemented — pass data_file=None.")
        rng = np.random.RandomState(11 if mode == "train" else 12)
        n = 1024 if mode == "train" else 256
        self.user = rng.randint(0, 943, (n,)).astype(np.int64)
        self.movie = rng.randint(0, 1682, (n,)).astype(np.int64)
        self.age = rng.randint(0, 7, (n,)).astype(np.int64)
        self.job = rng.randint(0, 21, (n,)).astype(np.int64)
        self.rating = rng.randint(1, 6, (n,)).astype(np.float32)

    def __getitem__(self, idx):
        return (self.user[idx], self.age[idx], self.job[idx],
                self.movie[idx], self.rating[idx])

    def __len__(self):
        return len(self.user)


class Imikolov(Dataset):
    """PTB n-gram language-model dataset schema (reference
    text/datasets/imikolov.py): data_type NGRAM yields (context..., target)
    tuples over a small vocab.  Synthetic payload (zero-egress)."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50):
        if data_file is not None:
            raise NotImplementedError(
                "Imikolov serves synthetic n-gram data only (zero-egress "
                "build); pass data_file=None")
        rng = np.random.RandomState(51 if mode == "train" else 52)
        vocab = 2000
        n = 2048 if mode == "train" else 256
        self.window_size = window_size
        stream = rng.randint(0, vocab, n + window_size).astype(np.int64)
        self.samples = [tuple(stream[i:i + window_size])
                        for i in range(n)]
        self._word_idx = {f"w{i}": i for i in range(vocab)}

    def word_idx(self):
        return self._word_idx

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class _WMT(Dataset):
    """Translation-pair schema: (src_ids, trg_ids, trg_ids_next)
    (reference text/datasets/wmt14.py)."""

    DICT_SIZE = 3000

    def __init__(self, data_file=None, mode="train", dict_size=-1,
                 lang="en"):
        if data_file is not None:
            raise NotImplementedError(
                f"{type(self).__name__} serves synthetic translation pairs "
                "only (zero-egress build); pass data_file=None")
        self.dict_size = self.DICT_SIZE if dict_size < 0 else dict_size
        rng = np.random.RandomState(61 if mode == "train" else 62)
        n = 512 if mode == "train" else 64
        self.samples = []
        for _ in range(n):
            ls = rng.randint(4, 20)
            lt = rng.randint(4, 20)
            src = rng.randint(0, self.dict_size, ls).astype(np.int64)
            trg = rng.randint(0, self.dict_size, lt).astype(np.int64)
            trg_next = np.concatenate([trg[1:], [1]]).astype(np.int64)
            self.samples.append((src, trg, trg_next))

    def get_dict(self, lang="en", reverse=False):
        d = {f"tok{i}": i for i in range(self.dict_size)}
        return {v: k for k, v in d.items()} if reverse else d

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class WMT14(_WMT):
    pass


class WMT16(_WMT):
    def get_dict(self, lang="en", reverse=False):
        return super().get_dict(lang, reverse)


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    from ..ops.misc import viterbi_decode as _impl
    return _impl(potentials, transition_params, lengths,
                 include_bos_eos_tag)


class ViterbiDecoder:
    """Layer wrapper holding the transitions (reference
    text/viterbi_decode.py ViterbiDecoder)."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
