"""paddle.fft namespace (reference python/paddle/fft.py — 1:1 API over the
cuFFT kernels; here each transform is one dispatched XLA op over jnp.fft).
"""
from __future__ import annotations

import jax.numpy as jnp

from .core import dispatch as D

__all__ = ["fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
           "fft2", "ifft2", "rfft2", "irfft2", "hfft2", "ihfft2",
           "fftn", "ifftn", "rfftn", "irfftn", "hfftn", "ihfftn",
           "fftfreq", "rfftfreq", "fftshift", "ifftshift"]


def _norm(norm):
    # paddle uses "backward"/"forward"/"ortho" like numpy
    return norm if norm is not None else "backward"


def _fft1(jfn, name):
    def op(x, n=None, axis=-1, norm="backward", name_arg=None):
        return D.apply(name, lambda a, n, axis, norm: jfn(a, n, axis, norm),
                       (x,), {"n": n, "axis": int(axis), "norm": _norm(norm)})
    op.__name__ = name
    return op


fft = _fft1(jnp.fft.fft, "fft")
ifft = _fft1(jnp.fft.ifft, "ifft")
rfft = _fft1(jnp.fft.rfft, "rfft")
irfft = _fft1(jnp.fft.irfft, "irfft")
hfft = _fft1(jnp.fft.hfft, "hfft")
ihfft = _fft1(jnp.fft.ihfft, "ihfft")


def _fftn(jfn, name):
    def op(x, s=None, axes=None, norm="backward", name_arg=None):
        s_t = tuple(s) if s is not None else None
        ax_t = tuple(axes) if axes is not None else None
        return D.apply(name, lambda a, s, axes, norm: jfn(a, s, axes, norm),
                       (x,), {"s": s_t, "axes": ax_t, "norm": _norm(norm)})
    op.__name__ = name
    return op


fftn = _fftn(jnp.fft.fftn, "fftn")
ifftn = _fftn(jnp.fft.ifftn, "ifftn")
rfftn = _fftn(jnp.fft.rfftn, "rfftn")
irfftn = _fftn(jnp.fft.irfftn, "irfftn")


def _fft2(nfn, name):
    def op(x, s=None, axes=(-2, -1), norm="backward", name_arg=None):
        return nfn(x, s, axes, norm)
    op.__name__ = name
    return op


fft2 = _fft2(fftn, "fft2")
ifft2 = _fft2(ifftn, "ifft2")
rfft2 = _fft2(rfftn, "rfft2")
irfft2 = _fft2(irfftn, "irfft2")


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    ax = tuple(axes) if axes is not None else (-1,)
    out = x
    for a in ax[:-1]:
        out = ifft(out, axis=a, norm=norm)
    return hfft(out, n=(s[-1] if s else None), axis=ax[-1], norm=norm)


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    ax = tuple(axes) if axes is not None else (-1,)
    out = ihfft(x, n=(s[-1] if s else None), axis=ax[-1], norm=norm)
    for a in ax[:-1]:
        out = fft(out, axis=a, norm=norm)
    return out


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return hfftn(x, s, axes, norm)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ihfftn(x, s, axes, norm)


def fftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor
    return Tensor(jnp.fft.fftfreq(int(n), float(d)).astype(
        jnp.float32 if dtype is None else dtype))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor
    return Tensor(jnp.fft.rfftfreq(int(n), float(d)).astype(
        jnp.float32 if dtype is None else dtype))


def fftshift(x, axes=None, name=None):
    ax = tuple(axes) if isinstance(axes, (list, tuple)) else axes
    return D.apply("fftshift", lambda a, axes: jnp.fft.fftshift(a, axes),
                   (x,), {"axes": ax})


def ifftshift(x, axes=None, name=None):
    ax = tuple(axes) if isinstance(axes, (list, tuple)) else axes
    return D.apply("ifftshift", lambda a, axes: jnp.fft.ifftshift(a, axes),
                   (x,), {"axes": ax})
