"""Graph learning ops (message passing + sampling).

Capability parity with /root/reference/python/paddle/geometric/
(message_passing/send_recv.py send_u_recv/send_ue_recv/send_uv, math.py
segment_* reductions, sampling/neighbors.py sample_neighbors; phi kernels
paddle/phi/kernels/gpu/graph_send_*).  TPU-native: every reduction lowers
to jax.ops.segment_* (one XLA scatter), gather stays a take — no custom
CUDA kernels needed.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dispatch as D
from ..core.tensor import Tensor

__all__ = ["send_u_recv", "send_ue_recv", "send_uv", "segment_sum",
           "segment_mean", "segment_max", "segment_min", "sample_neighbors",
           "reindex_graph", "weighted_sample_neighbors",
           "reindex_heter_graph"]


_SEGMENT = {
    "sum": jax.ops.segment_sum,
    "mean": None,  # composed
    "max": jax.ops.segment_max,
    "min": jax.ops.segment_min,
}


def _segment_reduce(data, seg, num, pool):
    if pool == "mean":
        s = jax.ops.segment_sum(data, seg, num_segments=num)
        cnt = jax.ops.segment_sum(jnp.ones((data.shape[0],), data.dtype),
                                  seg, num_segments=num)
        return s / jnp.maximum(cnt, 1.0)[(...,) + (None,) * (data.ndim - 1)]
    out = _SEGMENT[pool](data, seg, num_segments=num)
    if pool in ("max", "min"):
        # empty segments give +-inf in XLA; the reference zeroes them
        out = jnp.where(jnp.isfinite(out), out, jnp.zeros((), out.dtype))
    return out


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x[src] along edges, reduce at dst
    (reference send_recv.py send_u_recv)."""
    def impl(x, src, dst, reduce_op, out_size):
        num = out_size if out_size is not None else x.shape[0]
        msgs = jnp.take(x, src, axis=0)
        return _segment_reduce(msgs, dst, num, reduce_op)

    return D.apply("send_u_recv", impl, (x, src_index, dst_index),
                   {"reduce_op": reduce_op,
                    "out_size": int(out_size) if out_size is not None
                    else None})


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Combine x[src] with edge features y, reduce at dst."""
    def impl(x, y, src, dst, message_op, reduce_op, out_size):
        num = out_size if out_size is not None else x.shape[0]
        m = jnp.take(x, src, axis=0)
        if message_op == "add":
            msgs = m + y
        elif message_op == "sub":
            msgs = m - y
        elif message_op == "mul":
            msgs = m * y
        elif message_op == "div":
            msgs = m / y
        else:
            raise ValueError(f"unknown message_op {message_op!r}")
        return _segment_reduce(msgs, dst, num, reduce_op)

    return D.apply("send_ue_recv", impl, (x, y, src_index, dst_index),
                   {"message_op": message_op, "reduce_op": reduce_op,
                    "out_size": int(out_size) if out_size is not None
                    else None})


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message from both endpoints (reference send_uv)."""
    def impl(x, y, src, dst, message_op):
        xu = jnp.take(x, src, axis=0)
        yv = jnp.take(y, dst, axis=0)
        if message_op == "add":
            return xu + yv
        if message_op == "sub":
            return xu - yv
        if message_op == "mul":
            return xu * yv
        if message_op == "div":
            return xu / yv
        raise ValueError(f"unknown message_op {message_op!r}")

    return D.apply("send_uv", impl, (x, y, src_index, dst_index),
                   {"message_op": message_op})


def _make_segment(pool):
    def fn(data, segment_ids, name=None):
        # segment count must be static: computed from the (host) ids
        seg = segment_ids._data if isinstance(segment_ids, Tensor) \
            else jnp.asarray(segment_ids)
        num = int(jnp.max(seg)) + 1 if seg.size else 0

        def impl2(data, seg, pool, num):
            return _segment_reduce(data, seg, num, pool)

        return D.apply(f"segment_{pool}", impl2, (data, segment_ids),
                       {"pool": pool, "num": num})
    fn.__name__ = f"segment_{pool}"
    return fn


segment_sum = _make_segment("sum")
segment_mean = _make_segment("mean")
segment_max = _make_segment("max")
segment_min = _make_segment("min")


def sample_neighbors(row, colptr, input_nodes, sample_size=-1,
                     eids=None, return_eids=False, perm_buffer=None,
                     name=None):
    """Uniform neighbor sampling on a CSC graph (reference
    sampling/neighbors.py).  Host-side (graph sampling is data loading, not
    device compute — the reference runs it on CPU too).  Draws fresh
    randomness per call (OS entropy), like the reference's unseeded
    thread-local generators."""
    rng = np.random.default_rng()
    row_np = np.asarray(row.numpy() if isinstance(row, Tensor) else row)
    ptr = np.asarray(colptr.numpy() if isinstance(colptr, Tensor) else colptr)
    nodes = np.asarray(input_nodes.numpy()
                       if isinstance(input_nodes, Tensor) else input_nodes)
    out_n, out_count = [], []
    for v in nodes:
        beg, end = int(ptr[v]), int(ptr[v + 1])
        neigh = row_np[beg:end]
        if sample_size > 0 and len(neigh) > sample_size:
            neigh = rng.choice(neigh, size=sample_size, replace=False)
        out_n.append(neigh)
        out_count.append(len(neigh))
    out_neighbors = Tensor(jnp.asarray(
        np.concatenate(out_n) if out_n else np.zeros((0,), row_np.dtype)))
    out_counts = Tensor(jnp.asarray(np.asarray(out_count, np.int32)))
    return out_neighbors, out_counts


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Compact global node ids to local ids (reference reindex_graph)."""
    x_np = np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    n_np = np.asarray(neighbors.numpy()
                      if isinstance(neighbors, Tensor) else neighbors)
    uniq = list(dict.fromkeys(x_np.tolist()))
    mapping = {v: i for i, v in enumerate(uniq)}
    for v in n_np.tolist():
        if v not in mapping:
            mapping[v] = len(mapping)
            uniq.append(v)
    reindexed = np.asarray([mapping[v] for v in n_np.tolist()],
                           np.int64 if n_np.dtype.kind == "i" else n_np.dtype)
    nodes = np.asarray(uniq, x_np.dtype)
    return (Tensor(jnp.asarray(reindexed)),
            Tensor(jnp.asarray(nodes)),
            Tensor(jnp.asarray(np.asarray(count.numpy()
                                          if isinstance(count, Tensor)
                                          else count))))


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False,
                              name=None):
    """Weight-proportional neighbor sampling (reference
    weighted_sample_neighbors); host-side like sample_neighbors."""
    rng = np.random.default_rng()
    row_np = np.asarray(row.numpy() if isinstance(row, Tensor) else row)
    ptr = np.asarray(colptr.numpy() if isinstance(colptr, Tensor) else colptr)
    w = np.asarray(edge_weight.numpy() if isinstance(edge_weight, Tensor)
                   else edge_weight).astype(np.float64)
    nodes = np.asarray(input_nodes.numpy()
                       if isinstance(input_nodes, Tensor) else input_nodes)
    out_n, out_count = [], []
    for v in nodes:
        beg, end = int(ptr[v]), int(ptr[v + 1])
        neigh = row_np[beg:end]
        wv = w[beg:end]
        if sample_size > 0 and len(neigh) > sample_size:
            p = wv / wv.sum() if wv.sum() > 0 else None
            neigh = rng.choice(neigh, size=sample_size, replace=False, p=p)
        out_n.append(neigh)
        out_count.append(len(neigh))
    out_neighbors = Tensor(jnp.asarray(
        np.concatenate(out_n) if out_n else np.zeros((0,), row_np.dtype)))
    return out_neighbors, Tensor(jnp.asarray(np.asarray(out_count, np.int32)))


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Heterogeneous-graph reindex (reference reindex_heter_graph): one
    shared node mapping across per-edge-type neighbor lists."""
    x_np = np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    n_lists = [np.asarray(n.numpy() if isinstance(n, Tensor) else n)
               for n in neighbors]
    uniq = list(dict.fromkeys(x_np.tolist()))
    mapping = {v: i for i, v in enumerate(uniq)}
    outs = []
    for n_np in n_lists:
        for v in n_np.tolist():
            if v not in mapping:
                mapping[v] = len(mapping)
                uniq.append(v)
        outs.append(Tensor(jnp.asarray(np.asarray(
            [mapping[v] for v in n_np.tolist()], np.int64))))
    nodes = Tensor(jnp.asarray(np.asarray(uniq, x_np.dtype)))
    counts = [Tensor(jnp.asarray(np.asarray(
        c.numpy() if isinstance(c, Tensor) else c)))
        for c in count]
    return outs, nodes, counts
