"""paddle.signal namespace (reference python/paddle/signal.py: frame,
overlap_add, stft, istft)."""
from __future__ import annotations

import jax.numpy as jnp

from .core import dispatch as D

__all__ = ["frame", "overlap_add", "stft", "istft"]


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice x into overlapping frames along `axis`
    (reference signal.py frame)."""
    def impl(a, frame_length, hop_length, axis):
        ax = axis % a.ndim
        n = a.shape[ax]
        num = 1 + (n - frame_length) // hop_length
        idx = (jnp.arange(num)[:, None] * hop_length
               + jnp.arange(frame_length)[None, :])       # [num, L]
        out = jnp.take(a, idx.reshape(-1), axis=ax)
        shape = list(a.shape)
        shape[ax:ax + 1] = [num, frame_length]
        out = out.reshape(shape)
        # paddle layout: frame_length then num_frames on the last two dims
        if axis in (-1, a.ndim - 1):
            out = jnp.swapaxes(out, ax, ax + 1)
        return out
    return D.apply("frame", impl, (x,),
                   {"frame_length": int(frame_length),
                    "hop_length": int(hop_length), "axis": int(axis)})


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of frame (reference signal.py overlap_add): x has
    [..., frame_length, num_frames] on the trailing dims (axis=-1)."""
    def impl(a, hop_length, axis):
        if axis not in (0, -1):
            raise ValueError(
                "overlap_add: axis must be 0 or -1, got %r" % (axis,))
        if axis == -1:
            frames = jnp.swapaxes(a, -1, -2)    # [..., num, L]
        else:
            # axis=0 layout puts [num_frames, frame_length] on the LEADING
            # dims; move them (as [num, L]) to the end, fold, move back.
            frames = jnp.moveaxis(a, (0, 1), (-2, -1))  # [..., num, L]
        *batch, num, L = frames.shape
        n = (num - 1) * hop_length + L
        out = jnp.zeros((*batch, n), frames.dtype)
        for i in range(num):                    # static unroll: num is small
            out = out.at[..., i * hop_length:i * hop_length + L].add(
                frames[..., i, :])
        if axis == 0:
            out = jnp.moveaxis(out, -1, 0)      # [n, ...batch]
        return out
    return D.apply("overlap_add", impl, (x,),
                   {"hop_length": int(hop_length), "axis": int(axis)})


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """Short-time Fourier transform (reference signal.py stft).
    x: [B, T] or [T] real.  Returns [B, n_fft//2+1, num_frames] complex
    (onesided) like the reference."""
    hop = hop_length if hop_length is not None else n_fft // 4
    wl = win_length if win_length is not None else n_fft
    args = (x,) + ((window,) if window is not None else ())

    def impl(a, *rest, n_fft, hop, wl, center, pad_mode, normalized,
             onesided, has_window):
        w = rest[0] if has_window else jnp.ones((wl,), jnp.float32)
        if wl < n_fft:  # center-pad window to n_fft
            lp = (n_fft - wl) // 2
            w = jnp.pad(w, (lp, n_fft - wl - lp))
        squeeze = a.ndim == 1
        if squeeze:
            a = a[None]
        if center:
            a = jnp.pad(a, ((0, 0), (n_fft // 2, n_fft // 2)),
                        mode=pad_mode)
        num = 1 + (a.shape[-1] - n_fft) // hop
        idx = (jnp.arange(num)[:, None] * hop
               + jnp.arange(n_fft)[None, :])
        frames = a[:, idx] * w[None, None, :]             # [B, num, n_fft]
        spec = (jnp.fft.rfft(frames, axis=-1) if onesided
                else jnp.fft.fft(frames, axis=-1))
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        out = jnp.swapaxes(spec, -1, -2)                  # [B, F, num]
        return out[0] if squeeze else out
    return D.apply("stft", impl, args,
                   {"n_fft": int(n_fft), "hop": int(hop), "wl": int(wl),
                    "center": bool(center), "pad_mode": pad_mode,
                    "normalized": bool(normalized),
                    "onesided": bool(onesided),
                    "has_window": window is not None})


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """Inverse STFT with window-square normalization
    (reference signal.py istft)."""
    hop = hop_length if hop_length is not None else n_fft // 4
    wl = win_length if win_length is not None else n_fft
    args = (x,) + ((window,) if window is not None else ())

    def impl(spec, *rest, n_fft, hop, wl, center, normalized, onesided,
             length, has_window):
        w = rest[0] if has_window else jnp.ones((wl,), jnp.float32)
        if wl < n_fft:
            lp = (n_fft - wl) // 2
            w = jnp.pad(w, (lp, n_fft - wl - lp))
        squeeze = spec.ndim == 2
        if squeeze:
            spec = spec[None]
        frames_f = jnp.swapaxes(spec, -1, -2)             # [B, num, F]
        if normalized:
            frames_f = frames_f * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        frames = (jnp.fft.irfft(frames_f, n=n_fft, axis=-1) if onesided
                  else jnp.fft.ifft(frames_f, axis=-1).real)
        frames = frames * w[None, None, :]
        B, num, _ = frames.shape
        n = (num - 1) * hop + n_fft
        out = jnp.zeros((B, n), frames.dtype)
        wsq = jnp.zeros((n,), jnp.float32)
        for i in range(num):
            out = out.at[:, i * hop:i * hop + n_fft].add(frames[:, i])
            wsq = wsq.at[i * hop:i * hop + n_fft].add(w * w)
        out = out / jnp.maximum(wsq, 1e-11)[None, :]
        if center:
            out = out[:, n_fft // 2: n - n_fft // 2]
        if length is not None:
            out = out[:, :length]
        return out[0] if squeeze else out
    return D.apply("istft", impl, args,
                   {"n_fft": int(n_fft), "hop": int(hop), "wl": int(wl),
                    "center": bool(center), "normalized": bool(normalized),
                    "onesided": bool(onesided), "length": length,
                    "has_window": window is not None})
