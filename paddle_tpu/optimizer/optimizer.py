"""Optimizer base.

Parity with /root/reference/python/paddle/optimizer/optimizer.py:128 —
accumulators, grad clip, regularization, per-param lr, LRScheduler handling,
master weights for AMP O2.  Updates run as one fused, jit-compiled XLA program
over all parameters (the TPU analog of the reference's fused/multi_tensor
optimizer paths), with buffer donation so parameter memory is reused in place.
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from .lr import LRScheduler

__all__ = ["Optimizer"]


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        self._learning_rate = learning_rate
        self._parameter_list = list(parameters) if parameters is not None else None
        self._grad_clip = grad_clip
        if isinstance(weight_decay, (int, float)):
            self._coupled_wd = float(weight_decay)
        else:
            self._coupled_wd = 0.0
            self.regularization = weight_decay
        self._accumulators: dict[int, dict[str, jnp.ndarray]] = {}
        self._step_fn_cache = {}
        self._global_step = 0

    # ---- lr ----
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    # ---- accumulators ----
    def _slot_names(self):
        """Names of per-param state slots, e.g. ('moment1','moment2')."""
        return ()

    def _init_slot(self, name, p):
        # accumulators stay float32 even for low-precision params (the
        # reference multi-precision contract; bf16 moments quantize badly)
        if jnp.issubdtype(p._data.dtype, jnp.floating):
            return jnp.zeros(p._data.shape, jnp.float32)
        return jnp.zeros_like(p._data)

    def _state_for(self, p):
        st = self._accumulators.get(id(p))
        if st is None:
            st = {name: self._init_slot(name, p) for name in self._slot_names()}
            self._accumulators[id(p)] = st
        return st

    # ---- the update rule (pure; jit-compiled) ----
    def _update(self, p, g, state, lr, param_lr=1.0):
        """Return (new_p, new_state_dict).  Pure function of arrays."""
        raise NotImplementedError

    # ---- step ----
    def _collect_params_grads(self):
        params = self._parameter_list or []
        return [(p, p._grad) for p in params if not p.stop_gradient]

    def _compiled_step(self, key):
        """One XLA program updating every parameter: donates params+state.
        param_lrs/wds are static (baked into the program, part of the key).
        Cached per-instance so dropping the optimizer frees its executables."""
        cached = self._step_fn_cache.get(key)
        if cached is not None:
            return cached
        slot_names = tuple(self._slot_names())
        _, param_lrs, wds, masked, low_dts = key

        def run(params, grads, states, lr, extra, *maybe_mask):
            # masked variant: skip_mask is a DEVICE bool (AMP found_inf) —
            # when true the whole update is an identity, so the found_inf
            # decision never forces a host sync inside step()
            mask = maybe_mask[0] if masked else None
            new_params, new_states, new_lows = [], [], []
            for p, g, st, plr, wd, low in zip(params, grads, states,
                                              param_lrs, wds, low_dts):
                np_, nst = self._update_arrays(p, g, dict(zip(slot_names, st)),
                                              lr, plr, wd, extra)
                if masked:
                    np_ = jnp.where(mask, p, np_)
                    nst = {n: jnp.where(mask, st[i], nst[n])
                           for i, n in enumerate(slot_names)}
                new_params.append(np_)
                # AMP O2 master weights: update ran in f32 (p IS the master);
                # emit the low-precision working copy in the same program
                new_lows.append(np_.astype(low) if low is not None else None)
                new_states.append(tuple(nst[n] for n in slot_names))
            return new_params, new_states, new_lows

        exe = jax.jit(run, donate_argnums=(0, 2))
        self._step_fn_cache[key] = exe
        return exe

    def _update_arrays(self, p, g, state, lr, param_lr, wd, extra):
        raise NotImplementedError

    def _extra_args(self):
        """Extra dynamic scalars for the update (e.g. beta1 power)."""
        return ()

    def step(self):
        params_grads = [(p, g) for p, g in self._collect_params_grads() if g is not None]
        if not params_grads:
            self._global_step += 1
            return
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)

        self._global_step += 1
        # Pipeline-placed models keep each stage's params on its own device;
        # one XLA program can't mix committed devices, so run one fused
        # update per device group (the reference analog: per-stage optimizer
        # instances in PP training).  Under jit.capture_step the params are
        # tracers (no .devices()) — the whole update is one group inside the
        # enclosing program.
        by_dev = {}
        for pg in params_grads:
            try:
                key = tuple(sorted(d.id for d in pg[0]._data.devices()))
            except (AttributeError, jax.errors.ConcretizationTypeError):
                key = None
            by_dev.setdefault(key, []).append(pg)
        for group in by_dev.values():
            self._step_group(group)

    def _step_group(self, params_grads):
        # jit.capture_step threads the lr in as a dynamic input so schedulers
        # stepped between captured calls take effect without retracing
        ovr = getattr(self, "_lr_override", None)
        lr = ovr if ovr is not None else np.float32(self.get_lr())
        slot_names = tuple(self._slot_names())

        # AMP O2: params decorated with a float32 master copy update in f32
        # (reference optimizer.py multi-precision master-weight path); the
        # low-precision working copy is recast inside the fused program
        masters = [getattr(p, "_master_weight", None) for p, _ in params_grads]
        params = [m if m is not None else p._data
                  for (p, _), m in zip(params_grads, masters)]
        low_dts = tuple(str(p._data.dtype) if m is not None else None
                        for (p, _), m in zip(params_grads, masters))
        # L1 regularization: grad += coeff * sign(p) (reference
        # L1DecayRegularizer appends the same term pre-update)
        grads = []
        for p, g in params_grads:
            l1 = self._l1_coeff_for(p)
            gd = g._data
            if l1:
                gd = gd + jnp.asarray(l1, gd.dtype) * jnp.sign(p._data)
            grads.append(gd)
        states = []
        for p, _ in params_grads:
            st = self._state_for(p)
            states.append(tuple(st[n] for n in slot_names))
        param_lrs = tuple(
            float(getattr(p, "optimize_attr", None) and
                  p.optimize_attr.get("learning_rate", 1.0) or 1.0)
            for p, _ in params_grads)
        wds = tuple(self._weight_decay_for(p) for p, _ in params_grads)
        t_dyn = getattr(self, "_step_t_override", None)
        if t_dyn is not None:
            # captured step: extra scalars (bias corrections etc.) must be
            # functions of the DYNAMIC step input, not of the baked host int
            dyn = getattr(self, "_extra_args_dynamic", None)
            if dyn is None and type(self)._extra_args is not Optimizer._extra_args:
                raise NotImplementedError(
                    f"{type(self).__name__} computes host-side per-step "
                    "state and cannot run under jit.capture_step; use "
                    "Adam/AdamW/Adamax/Lamb/SGD/Momentum/ASGD or run eager")
            extra = dyn(t_dyn) if dyn is not None else ()
        else:
            extra = self._extra_args()

        mask = getattr(self, "_skip_update_mask", None)
        key = (tuple((tuple(p.shape), str(p.dtype)) for p in params),
               param_lrs, wds, mask is not None, low_dts)
        args = (params, grads, states, lr, extra)
        if mask is not None:
            args = args + (mask,)
        new_params, new_states, new_lows = self._compiled_step(key)(*args)

        for (p, _), np_, nst, nl in zip(params_grads, new_params,
                                        new_states, new_lows):
            if nl is not None:
                p._master_weight = np_
                p._data = nl
            else:
                p._data = np_
            st = self._accumulators[id(p)]
            for n, v in zip(slot_names, nst):
                st[n] = v

    def _weight_decay_for(self, p):
        reg = getattr(p, "regularizer", None)
        if reg is None:
            reg = getattr(self, "regularization", None)
        if reg is not None:
            # L1 contributes sign(p) to the grad (see _apply_l1); only L2
            # rides the fused decay slot
            return 0.0 if getattr(reg, "_l1", False) else float(reg._coeff)
        return self._coupled_wd

    def _l1_coeff_for(self, p):
        reg = getattr(p, "regularizer", None)
        if reg is None:
            reg = getattr(self, "regularization", None)
        if reg is not None and getattr(reg, "_l1", False):
            return float(reg._coeff)
        return 0.0

    def clear_grad(self, set_to_zero=True):
        for p in (self._parameter_list or []):
            p.clear_grad()

    clear_gradients = clear_grad

    def state_dict(self):
        out = {"global_step": self._global_step}
        if isinstance(self._learning_rate, LRScheduler):
            out["LR_Scheduler"] = self._learning_rate.state_dict()
        params = self._parameter_list or []
        for p in params:
            st = self._accumulators.get(id(p))
            if st:
                for name, v in st.items():
                    out[f"{p.name}_{name}"] = Tensor(v)
        return out

    def set_state_dict(self, state_dict):
        self._global_step = state_dict.get("global_step", 0)
        if isinstance(self._learning_rate, LRScheduler) and "LR_Scheduler" in state_dict:
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])
        params = self._parameter_list or []
        for p in params:
            st = {}
            for name in self._slot_names():
                key = f"{p.name}_{name}"
                if key in state_dict:
                    v = state_dict[key]
                    st[name] = v._data if isinstance(v, Tensor) else jnp.asarray(v)
            if st:
                full = {n: st.get(n, self._init_slot(n, p)) for n in self._slot_names()}
                self._accumulators[id(p)] = full

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None

    def _apply_optimize(self, loss, startup_program, params_grads):
        self.step()
