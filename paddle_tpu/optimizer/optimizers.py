"""Concrete optimizers: SGD, Momentum, Adam, AdamW, Adagrad, Adadelta,
Adamax, RMSProp, Lamb.

Parity with /root/reference/python/paddle/optimizer/{sgd,momentum,adam,adamw,
adagrad,adadelta,adamax,rmsprop,lamb}.py.  Update rules are pure array
functions compiled into one donated XLA program per step (Optimizer base).
"""
from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer

__all__ = ["SGD", "Momentum", "Adam", "AdamW", "Adagrad", "Adadelta", "Adamax",
           "RMSProp", "Lamb"]


def _wd_grad(p, g, wd):
    # L2Decay-style coupled decay: grad += wd * param
    if wd:
        g = g + wd * p.astype(g.dtype)
    return g


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _update_arrays(self, p, g, state, lr, param_lr, wd, extra):
        g = _wd_grad(p, g.astype(jnp.float32), wd)
        new_p = p - (lr * param_lr) * g.astype(p.dtype)
        return new_p.astype(p.dtype), state


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = float(momentum)
        self._nesterov = bool(use_nesterov)

    def _slot_names(self):
        return ("velocity",)

    def _update_arrays(self, p, g, state, lr, param_lr, wd, extra):
        mu = self._momentum
        g = _wd_grad(p, g.astype(jnp.float32), wd)
        v = mu * state["velocity"] + g
        if self._nesterov:
            upd = g + mu * v
        else:
            upd = v
        new_p = p - (lr * param_lr) * upd.astype(p.dtype)
        return new_p.astype(p.dtype), {"velocity": v}

    def _init_slot(self, name, p):
        return jnp.zeros(p._data.shape, jnp.float32)


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, use_multi_tensor=False,
                 amsgrad=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1 = float(beta1)
        self._beta2 = float(beta2)
        self._epsilon = float(epsilon)
        self._amsgrad = amsgrad

    def _slot_names(self):
        return ("moment1", "moment2") + (("moment2_max",) if self._amsgrad else ())

    def _init_slot(self, name, p):
        return jnp.zeros(p._data.shape, jnp.float32)

    def _extra_args(self):
        t = self._global_step
        return (jnp.asarray(1.0 - self._beta1 ** t, jnp.float32),
                jnp.asarray(1.0 - self._beta2 ** t, jnp.float32))

    def _decoupled(self):
        return False

    def _update_arrays(self, p, g, state, lr, param_lr, wd, extra):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        bc1, bc2 = extra
        gf = g.astype(jnp.float32)
        pf = p.astype(jnp.float32)
        step_lr = lr * param_lr
        if wd and not self._decoupled():
            gf = gf + wd * pf
        m = b1 * state["moment1"] + (1 - b1) * gf
        v = b2 * state["moment2"] + (1 - b2) * gf * gf
        m_hat = m / bc1
        if self._amsgrad:
            v_max = jnp.maximum(state.get("moment2_max", v), v)
            v_hat = v_max / bc2
        else:
            v_hat = v / bc2
        upd = m_hat / (jnp.sqrt(v_hat) + eps)
        if wd and self._decoupled():
            pf = pf * (1.0 - step_lr * wd)
        new_p = pf - step_lr * upd
        new_state = {"moment1": m, "moment2": v}
        if self._amsgrad:
            new_state["moment2_max"] = v_max
        return new_p.astype(p.dtype), new_state


class AdamW(Adam):
    """Decoupled weight decay (/root/reference/python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None,
                 apply_decay_param_fun=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, amsgrad=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision,
                         amsgrad=amsgrad, name=name)
        self._wd_value = float(weight_decay) if isinstance(weight_decay, (int, float)) \
            else float(getattr(weight_decay, "_coeff", 0.0))
        self._apply_decay_param_fun = apply_decay_param_fun

    def _decoupled(self):
        return True

    def _weight_decay_for(self, p):
        if self._apply_decay_param_fun is not None and not self._apply_decay_param_fun(p.name):
            return 0.0
        return self._wd_value


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = float(epsilon)
        self._init_val = float(initial_accumulator_value)

    def _slot_names(self):
        return ("moment",)

    def _init_slot(self, name, p):
        return jnp.full(p._data.shape, self._init_val, jnp.float32)

    def _update_arrays(self, p, g, state, lr, param_lr, wd, extra):
        gf = _wd_grad(p, g.astype(jnp.float32), wd)
        mom = state["moment"] + gf * gf
        new_p = p.astype(jnp.float32) - (lr * param_lr) * gf / (jnp.sqrt(mom) + self._epsilon)
        return new_p.astype(p.dtype), {"moment": mom}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = float(epsilon)
        self._rho = float(rho)

    def _slot_names(self):
        return ("avg_squared_grad", "avg_squared_update")

    def _init_slot(self, name, p):
        return jnp.zeros(p._data.shape, jnp.float32)

    def _update_arrays(self, p, g, state, lr, param_lr, wd, extra):
        rho, eps = self._rho, self._epsilon
        gf = _wd_grad(p, g.astype(jnp.float32), wd)
        asg = rho * state["avg_squared_grad"] + (1 - rho) * gf * gf
        upd = gf * jnp.sqrt(state["avg_squared_update"] + eps) / jnp.sqrt(asg + eps)
        asu = rho * state["avg_squared_update"] + (1 - rho) * upd * upd
        new_p = p.astype(jnp.float32) - (lr * param_lr) * upd
        return new_p.astype(p.dtype), {"avg_squared_grad": asg, "avg_squared_update": asu}


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = float(beta1), float(beta2), float(epsilon)

    def _slot_names(self):
        return ("moment", "inf_norm")

    def _init_slot(self, name, p):
        return jnp.zeros(p._data.shape, jnp.float32)

    def _extra_args(self):
        return (jnp.asarray(1.0 - self._beta1 ** self._global_step, jnp.float32),)

    def _update_arrays(self, p, g, state, lr, param_lr, wd, extra):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        (bc1,) = extra
        gf = _wd_grad(p, g.astype(jnp.float32), wd)
        m = b1 * state["moment"] + (1 - b1) * gf
        inf = jnp.maximum(b2 * state["inf_norm"], jnp.abs(gf))
        new_p = p.astype(jnp.float32) - (lr * param_lr) / bc1 * m / (inf + eps)
        return new_p.astype(p.dtype), {"moment": m, "inf_norm": inf}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._epsilon = float(rho), float(epsilon)
        self._momentum = float(momentum)
        self._centered = centered

    def _slot_names(self):
        return ("mean_square", "momentum") + (("mean_grad",) if self._centered else ())

    def _init_slot(self, name, p):
        return jnp.zeros(p._data.shape, jnp.float32)

    def _update_arrays(self, p, g, state, lr, param_lr, wd, extra):
        rho, eps, mu = self._rho, self._epsilon, self._momentum
        gf = _wd_grad(p, g.astype(jnp.float32), wd)
        ms = rho * state["mean_square"] + (1 - rho) * gf * gf
        new_state = {"mean_square": ms}
        if self._centered:
            mg = rho * state["mean_grad"] + (1 - rho) * gf
            denom = jnp.sqrt(ms - mg * mg + eps)
            new_state["mean_grad"] = mg
        else:
            denom = jnp.sqrt(ms + eps)
        mom = mu * state["momentum"] + (lr * param_lr) * gf / denom
        new_state["momentum"] = mom
        new_p = p.astype(jnp.float32) - mom
        return new_p.astype(p.dtype), new_state


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = float(beta1), float(beta2), float(epsilon)
        self._lamb_wd = float(lamb_weight_decay)
        self._exclude_fn = exclude_from_weight_decay_fn

    def _slot_names(self):
        return ("moment1", "moment2")

    def _init_slot(self, name, p):
        return jnp.zeros(p._data.shape, jnp.float32)

    def _extra_args(self):
        t = self._global_step
        return (jnp.asarray(1.0 - self._beta1 ** t, jnp.float32),
                jnp.asarray(1.0 - self._beta2 ** t, jnp.float32))

    def _weight_decay_for(self, p):
        if self._exclude_fn is not None and self._exclude_fn(p):
            return 0.0
        return self._lamb_wd

    def _update_arrays(self, p, g, state, lr, param_lr, wd, extra):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        bc1, bc2 = extra
        gf = g.astype(jnp.float32)
        pf = p.astype(jnp.float32)
        m = b1 * state["moment1"] + (1 - b1) * gf
        v = b2 * state["moment2"] + (1 - b2) * gf * gf
        r = (m / bc1) / (jnp.sqrt(v / bc2) + eps) + wd * pf
        w_norm = jnp.sqrt(jnp.sum(pf * pf))
        r_norm = jnp.sqrt(jnp.sum(r * r))
        ratio = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        new_p = pf - (lr * param_lr) * ratio * r
        return new_p.astype(p.dtype), {"moment1": m, "moment2": v}
