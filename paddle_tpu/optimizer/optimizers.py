"""Concrete optimizers: SGD, Momentum, Adam, AdamW, Adagrad, Adadelta,
Adamax, RMSProp, Lamb.

Parity with /root/reference/python/paddle/optimizer/{sgd,momentum,adam,adamw,
adagrad,adadelta,adamax,rmsprop,lamb}.py.  Update rules are pure array
functions compiled into one donated XLA program per step (Optimizer base).
"""
from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer

__all__ = ["SGD", "Momentum", "Adam", "AdamW", "Adagrad", "Adadelta", "Adamax",
           "RMSProp", "Lamb", "NAdam", "RAdam", "ASGD", "Rprop", "LBFGS"]


def _wd_grad(p, g, wd):
    # L2Decay-style coupled decay: grad += wd * param
    if wd:
        g = g + wd * p.astype(g.dtype)
    return g


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _update_arrays(self, p, g, state, lr, param_lr, wd, extra):
        g = _wd_grad(p, g.astype(jnp.float32), wd)
        new_p = p - (lr * param_lr) * g.astype(p.dtype)
        return new_p.astype(p.dtype), state


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = float(momentum)
        self._nesterov = bool(use_nesterov)

    def _slot_names(self):
        return ("velocity",)

    def _update_arrays(self, p, g, state, lr, param_lr, wd, extra):
        mu = self._momentum
        g = _wd_grad(p, g.astype(jnp.float32), wd)
        v = mu * state["velocity"] + g
        if self._nesterov:
            upd = g + mu * v
        else:
            upd = v
        new_p = p - (lr * param_lr) * upd.astype(p.dtype)
        return new_p.astype(p.dtype), {"velocity": v}

    def _init_slot(self, name, p):
        return jnp.zeros(p._data.shape, jnp.float32)


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, use_multi_tensor=False,
                 amsgrad=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1 = float(beta1)
        self._beta2 = float(beta2)
        self._epsilon = float(epsilon)
        self._amsgrad = amsgrad

    def _slot_names(self):
        return ("moment1", "moment2") + (("moment2_max",) if self._amsgrad else ())

    def _init_slot(self, name, p):
        return jnp.zeros(p._data.shape, jnp.float32)

    def _extra_args(self):
        # host scalars: jnp.asarray here would run two eager device ops
        # per step (profiled at ~30% of optimizer host time)
        import numpy as _np
        t = self._global_step
        return (_np.float32(1.0 - self._beta1 ** t),
                _np.float32(1.0 - self._beta2 ** t))

    def _extra_args_dynamic(self, t):
        tf = t.astype(jnp.float32)
        return (1.0 - jnp.asarray(self._beta1, jnp.float32) ** tf,
                1.0 - jnp.asarray(self._beta2, jnp.float32) ** tf)

    def _decoupled(self):
        return False

    def _update_arrays(self, p, g, state, lr, param_lr, wd, extra):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        bc1, bc2 = extra
        gf = g.astype(jnp.float32)
        pf = p.astype(jnp.float32)
        step_lr = lr * param_lr
        if wd and not self._decoupled():
            gf = gf + wd * pf
        m = b1 * state["moment1"] + (1 - b1) * gf
        v = b2 * state["moment2"] + (1 - b2) * gf * gf
        m_hat = m / bc1
        if self._amsgrad:
            v_max = jnp.maximum(state.get("moment2_max", v), v)
            v_hat = v_max / bc2
        else:
            v_hat = v / bc2
        upd = m_hat / (jnp.sqrt(v_hat) + eps)
        if wd and self._decoupled():
            pf = pf * (1.0 - step_lr * wd)
        new_p = pf - step_lr * upd
        new_state = {"moment1": m, "moment2": v}
        if self._amsgrad:
            new_state["moment2_max"] = v_max
        return new_p.astype(p.dtype), new_state


class AdamW(Adam):
    """Decoupled weight decay (/root/reference/python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None,
                 apply_decay_param_fun=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, amsgrad=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision,
                         amsgrad=amsgrad, name=name)
        self._wd_value = float(weight_decay) if isinstance(weight_decay, (int, float)) \
            else float(getattr(weight_decay, "_coeff", 0.0))
        self._apply_decay_param_fun = apply_decay_param_fun

    def _decoupled(self):
        return True

    def _weight_decay_for(self, p):
        if self._apply_decay_param_fun is not None and not self._apply_decay_param_fun(p.name):
            return 0.0
        return self._wd_value


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = float(epsilon)
        self._init_val = float(initial_accumulator_value)

    def _slot_names(self):
        return ("moment",)

    def _init_slot(self, name, p):
        return jnp.full(p._data.shape, self._init_val, jnp.float32)

    def _update_arrays(self, p, g, state, lr, param_lr, wd, extra):
        gf = _wd_grad(p, g.astype(jnp.float32), wd)
        mom = state["moment"] + gf * gf
        new_p = p.astype(jnp.float32) - (lr * param_lr) * gf / (jnp.sqrt(mom) + self._epsilon)
        return new_p.astype(p.dtype), {"moment": mom}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = float(epsilon)
        self._rho = float(rho)

    def _slot_names(self):
        return ("avg_squared_grad", "avg_squared_update")

    def _init_slot(self, name, p):
        return jnp.zeros(p._data.shape, jnp.float32)

    def _update_arrays(self, p, g, state, lr, param_lr, wd, extra):
        rho, eps = self._rho, self._epsilon
        gf = _wd_grad(p, g.astype(jnp.float32), wd)
        asg = rho * state["avg_squared_grad"] + (1 - rho) * gf * gf
        upd = gf * jnp.sqrt(state["avg_squared_update"] + eps) / jnp.sqrt(asg + eps)
        asu = rho * state["avg_squared_update"] + (1 - rho) * upd * upd
        new_p = p.astype(jnp.float32) - (lr * param_lr) * upd
        return new_p.astype(p.dtype), {"avg_squared_grad": asg, "avg_squared_update": asu}


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = float(beta1), float(beta2), float(epsilon)

    def _slot_names(self):
        return ("moment", "inf_norm")

    def _init_slot(self, name, p):
        return jnp.zeros(p._data.shape, jnp.float32)

    def _extra_args(self):
        import numpy as _np
        return (_np.float32(1.0 - self._beta1 ** self._global_step),)

    def _extra_args_dynamic(self, t):
        return (1.0 - jnp.asarray(self._beta1, jnp.float32) ** t.astype(jnp.float32),)

    def _update_arrays(self, p, g, state, lr, param_lr, wd, extra):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        (bc1,) = extra
        gf = _wd_grad(p, g.astype(jnp.float32), wd)
        m = b1 * state["moment"] + (1 - b1) * gf
        inf = jnp.maximum(b2 * state["inf_norm"], jnp.abs(gf))
        new_p = p.astype(jnp.float32) - (lr * param_lr) / bc1 * m / (inf + eps)
        return new_p.astype(p.dtype), {"moment": m, "inf_norm": inf}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._epsilon = float(rho), float(epsilon)
        self._momentum = float(momentum)
        self._centered = centered

    def _slot_names(self):
        return ("mean_square", "momentum") + (("mean_grad",) if self._centered else ())

    def _init_slot(self, name, p):
        return jnp.zeros(p._data.shape, jnp.float32)

    def _update_arrays(self, p, g, state, lr, param_lr, wd, extra):
        rho, eps, mu = self._rho, self._epsilon, self._momentum
        gf = _wd_grad(p, g.astype(jnp.float32), wd)
        ms = rho * state["mean_square"] + (1 - rho) * gf * gf
        new_state = {"mean_square": ms}
        if self._centered:
            mg = rho * state["mean_grad"] + (1 - rho) * gf
            denom = jnp.sqrt(ms - mg * mg + eps)
            new_state["mean_grad"] = mg
        else:
            denom = jnp.sqrt(ms + eps)
        mom = mu * state["momentum"] + (lr * param_lr) * gf / denom
        new_state["momentum"] = mom
        new_p = p.astype(jnp.float32) - mom
        return new_p.astype(p.dtype), new_state


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = float(beta1), float(beta2), float(epsilon)
        self._lamb_wd = float(lamb_weight_decay)
        self._exclude_fn = exclude_from_weight_decay_fn

    def _slot_names(self):
        return ("moment1", "moment2")

    def _init_slot(self, name, p):
        return jnp.zeros(p._data.shape, jnp.float32)

    def _extra_args(self):
        import numpy as _np
        t = self._global_step
        return (_np.float32(1.0 - self._beta1 ** t),
                _np.float32(1.0 - self._beta2 ** t))

    def _extra_args_dynamic(self, t):
        tf = t.astype(jnp.float32)
        return (1.0 - jnp.asarray(self._beta1, jnp.float32) ** tf,
                1.0 - jnp.asarray(self._beta2, jnp.float32) ** tf)

    def _weight_decay_for(self, p):
        if self._exclude_fn is not None and self._exclude_fn(p):
            return 0.0
        return self._lamb_wd

    def _update_arrays(self, p, g, state, lr, param_lr, wd, extra):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        bc1, bc2 = extra
        gf = g.astype(jnp.float32)
        pf = p.astype(jnp.float32)
        m = b1 * state["moment1"] + (1 - b1) * gf
        v = b2 * state["moment2"] + (1 - b2) * gf * gf
        r = (m / bc1) / (jnp.sqrt(v / bc2) + eps) + wd * pf
        w_norm = jnp.sqrt(jnp.sum(pf * pf))
        r_norm = jnp.sqrt(jnp.sum(r * r))
        ratio = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        new_p = pf - (lr * param_lr) * ratio * r
        return new_p.astype(p.dtype), {"moment1": m, "moment2": v}


class NAdam(Optimizer):
    """Nesterov-momentum Adam (reference python/paddle/optimizer/nadam.py).

    The mu products are scalars depending only on the step count, so they
    are carried as host floats and fed per step (`_extra_args`) instead of
    per-parameter state."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, momentum_decay=0.004, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._beta1 = float(beta1)
        self._beta2 = float(beta2)
        self._epsilon = float(epsilon)
        self._psi = float(momentum_decay)
        self._mu_product = 1.0

    def _slot_names(self):
        return ("moment1", "moment2")

    def _init_slot(self, name, p):
        return jnp.zeros(p._data.shape, jnp.float32)

    def _extra_args(self):
        t = self._global_step
        mu_t = self._beta1 * (1.0 - 0.5 * 0.96 ** (t * self._psi))
        mu_t1 = self._beta1 * (1.0 - 0.5 * 0.96 ** ((t + 1) * self._psi))
        # running product is updated exactly once per step (extra args are
        # computed once per _step_group batch; guard with the step count)
        if getattr(self, "_mu_step", None) != t:
            self._mu_product *= mu_t
            self._mu_step = t
        mp_t = self._mu_product
        mp_t1 = mp_t * mu_t1
        return (jnp.asarray(mu_t, jnp.float32),
                jnp.asarray(mu_t1, jnp.float32),
                jnp.asarray(mp_t, jnp.float32),
                jnp.asarray(mp_t1, jnp.float32),
                jnp.asarray(1.0 - self._beta2 ** t, jnp.float32))

    def _update_arrays(self, p, g, state, lr, param_lr, wd, extra):
        b2, eps = self._beta2, self._epsilon
        mu_t, mu_t1, mp_t, mp_t1, bc2 = extra
        gf = _wd_grad(p, g.astype(jnp.float32), wd)
        m = self._beta1 * state["moment1"] + (1 - self._beta1) * gf
        v = b2 * state["moment2"] + (1 - b2) * gf * gf
        m_hat = mu_t1 * m / (1.0 - mp_t1) + (1.0 - mu_t) * gf / (1.0 - mp_t)
        v_hat = v / bc2
        new_p = p.astype(jnp.float32) - lr * param_lr * m_hat / (
            jnp.sqrt(v_hat) + eps)
        return new_p.astype(p.dtype), {"moment1": m, "moment2": v}

    # the running mu-product is host state the slot system does not carry;
    # without it a checkpoint resume would recompute wrong bias corrections
    def state_dict(self):
        out = super().state_dict()
        out["mu_product"] = self._mu_product
        return out

    def set_state_dict(self, state_dict):
        super().set_state_dict(state_dict)
        self._mu_product = float(state_dict.get("mu_product", 1.0))
        self._mu_step = self._global_step


class RAdam(Optimizer):
    """Rectified Adam (reference python/paddle/optimizer/radam.py): the
    variance rectification term switches on once rho_t > 4; before that the
    update is momentum-only."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._beta1 = float(beta1)
        self._beta2 = float(beta2)
        self._epsilon = float(epsilon)

    def _slot_names(self):
        return ("moment1", "moment2")

    def _init_slot(self, name, p):
        return jnp.zeros(p._data.shape, jnp.float32)

    def _extra_args(self):
        t = self._global_step
        b2 = self._beta2
        rho_inf = 2.0 / (1.0 - b2) - 1.0
        b2t = b2 ** t
        rho_t = rho_inf - 2.0 * t * b2t / (1.0 - b2t)
        if rho_t > 4.0:
            r = (((rho_t - 4.0) * (rho_t - 2.0) * rho_inf)
                 / ((rho_inf - 4.0) * (rho_inf - 2.0) * rho_t)) ** 0.5
        else:
            r = 0.0
        return (jnp.asarray(1.0 - self._beta1 ** t, jnp.float32),
                jnp.asarray(1.0 - b2t, jnp.float32),
                jnp.asarray(r, jnp.float32),
                jnp.asarray(1.0 if rho_t > 4.0 else 0.0, jnp.float32))

    def _update_arrays(self, p, g, state, lr, param_lr, wd, extra):
        bc1, bc2, r, rectified = extra
        gf = _wd_grad(p, g.astype(jnp.float32), wd)
        m = self._beta1 * state["moment1"] + (1 - self._beta1) * gf
        v = self._beta2 * state["moment2"] + (1 - self._beta2) * gf * gf
        m_hat = m / bc1
        v_hat = jnp.sqrt(v / bc2) + self._epsilon
        upd = jnp.where(rectified > 0.5, r * m_hat / v_hat, m_hat)
        new_p = p.astype(jnp.float32) - lr * param_lr * upd
        return new_p.astype(p.dtype), {"moment1": m, "moment2": v}


class ASGD(Optimizer):
    """Averaged SGD over the last `batch_num` gradients (reference
    python/paddle/optimizer/asgd.py: d <- d - ys[i] + g; ys[i] <- g;
    p <- p - lr/n * d, with ys an n-slot gradient ring)."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._batch_num = max(1, int(batch_num))

    def _slot_names(self):
        return ("d", "ys")

    def _init_slot(self, name, p):
        if name == "ys":
            return jnp.zeros((self._batch_num,) + tuple(p._data.shape),
                             jnp.float32)
        return jnp.zeros(p._data.shape, jnp.float32)

    def _extra_args(self):
        # ring index of the gradient being replaced this step
        return (jnp.asarray((self._global_step - 1) % self._batch_num,
                            jnp.int32),)

    def _extra_args_dynamic(self, t):
        return ((t.astype(jnp.int32) - 1) % self._batch_num,)

    def _update_arrays(self, p, g, state, lr, param_lr, wd, extra):
        import jax as _jax
        (idx,) = extra
        gf = _wd_grad(p, g.astype(jnp.float32), wd)
        old = _jax.lax.dynamic_index_in_dim(state["ys"], idx, axis=0,
                                            keepdims=False)
        d = state["d"] - old + gf
        ys = _jax.lax.dynamic_update_index_in_dim(state["ys"], gf, idx,
                                                  axis=0)
        new_p = p.astype(jnp.float32) - lr * param_lr * d / self._batch_num
        return new_p.astype(p.dtype), {"d": d, "ys": ys}


class Rprop(Optimizer):
    """Resilient backprop (reference python/paddle/optimizer/rprop.py):
    per-element step sizes grown on sign agreement, shrunk on disagreement
    (where the gradient is also zeroed), update = -sign(g) * step."""

    def __init__(self, learning_rate=0.001,
                 learning_rate_range=(1e-5, 50.0), parameters=None,
                 etas=(0.5, 1.2), grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._lr0 = float(learning_rate)
        self._lr_min, self._lr_max = (float(v) for v in learning_rate_range)
        self._eta_minus, self._eta_plus = (float(v) for v in etas)

    def _slot_names(self):
        return ("prev_grad", "steps")

    def _init_slot(self, name, p):
        if name == "steps":
            return jnp.full(p._data.shape, self._lr0, jnp.float32)
        return jnp.zeros(p._data.shape, jnp.float32)

    def _update_arrays(self, p, g, state, lr, param_lr, wd, extra):
        gf = g.astype(jnp.float32)
        sign = gf * state["prev_grad"]
        steps = jnp.where(
            sign > 0, jnp.minimum(state["steps"] * self._eta_plus,
                                  self._lr_max),
            jnp.where(sign < 0,
                      jnp.maximum(state["steps"] * self._eta_minus,
                                  self._lr_min),
                      state["steps"]))
        gf = jnp.where(sign < 0, 0.0, gf)
        new_p = p.astype(jnp.float32) - jnp.sign(gf) * steps
        return new_p.astype(p.dtype), {"prev_grad": gf, "steps": steps}


class LBFGS(Optimizer):
    """Limited-memory BFGS with closure re-evaluation (reference
    python/paddle/optimizer/lbfgs.py).  Two-loop recursion over a
    `history_size` window; line search is Armijo backtracking when
    `line_search_fn='strong_wolfe'` is requested (a sufficient-decrease
    subset of strong Wolfe — documented deviation) else a fixed lr step.
    """

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9,
                 history_size=100, line_search_fn=None, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._max_iter = int(max_iter)
        self._tol_grad = float(tolerance_grad)
        self._tol_change = float(tolerance_change)
        self._history = int(history_size)
        self._line_search = line_search_fn
        self._s: list = []
        self._y: list = []

    def _flat_params(self):
        return jnp.concatenate(
            [p._data.astype(jnp.float32).reshape(-1)
             for p in self._parameter_list])

    def _set_flat(self, vec):
        off = 0
        for p in self._parameter_list:
            n = int(p._data.size)
            p._data = vec[off:off + n].reshape(p._data.shape).astype(
                p._data.dtype)
            off += n

    def _flat_grad(self):
        gs = []
        for p in self._parameter_list:
            g = p.grad
            gs.append((jnp.zeros(p._data.shape, jnp.float32)
                       if g is None else g._data.astype(jnp.float32))
                      .reshape(-1))
        return jnp.concatenate(gs)

    def _direction(self, grad):
        q = -grad
        alphas = []
        for s, y in zip(reversed(self._s), reversed(self._y)):
            rho = 1.0 / jnp.vdot(y, s)
            a = rho * jnp.vdot(s, q)
            q = q - a * y
            alphas.append((a, rho, s, y))
        if self._s:
            s, y = self._s[-1], self._y[-1]
            q = q * (jnp.vdot(s, y) / jnp.vdot(y, y))
        for a, rho, s, y in reversed(alphas):
            b = rho * jnp.vdot(y, q)
            q = q + (a - b) * s
        return q

    def step(self, closure=None):
        if closure is None:
            raise ValueError("LBFGS.step requires a closure computing the "
                             "loss (reference lbfgs.py contract)")
        loss = closure()
        for _ in range(self._max_iter):
            grad = self._flat_grad()
            if float(jnp.max(jnp.abs(grad))) <= self._tol_grad:
                break
            d = self._direction(grad)
            x0 = self._flat_params()
            f0 = float(loss.numpy() if hasattr(loss, "numpy") else loss)
            t = self.get_lr()
            gtd = float(jnp.vdot(grad, d))
            accepted = False
            trials = 8 if self._line_search else 1
            for _ls in range(trials):
                self._set_flat(x0 + t * d)
                self.clear_grad()
                loss = closure()
                f1 = float(loss.numpy() if hasattr(loss, "numpy") else loss)
                if not self._line_search or f1 <= f0 + 1e-4 * t * gtd:
                    accepted = True
                    break
                t *= 0.5
            if not accepted:
                self._set_flat(x0)
                self.clear_grad()
                loss = closure()
                break
            g1 = self._flat_grad()
            s = self._flat_params() - x0
            y = g1 - grad
            if float(jnp.vdot(s, y)) > 1e-10:
                self._s.append(s)
                self._y.append(y)
                if len(self._s) > self._history:
                    self._s.pop(0)
                    self._y.pop(0)
            if float(jnp.max(jnp.abs(s))) <= self._tol_change:
                break
        self._global_step += 1
        return loss
