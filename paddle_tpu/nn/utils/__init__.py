"""nn.utils (reference python/paddle/nn/utils/: weight_norm_hook.py,
spectral_norm_hook.py, clip_grad_norm_.py, clip_grad_value_.py,
transform_parameters.py).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...core.tensor import Parameter, Tensor

__all__ = ["clip_grad_norm_", "clip_grad_value_", "parameters_to_vector",
           "vector_to_parameters", "weight_norm", "remove_weight_norm",
           "spectral_norm"]


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    """In-place global grad-norm clip; returns the pre-clip total norm
    (reference clip_grad_norm_.py)."""
    params = [parameters] if isinstance(parameters, Tensor) else \
        [p for p in parameters]
    grads = [p.grad for p in params if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros((), jnp.float32))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack(
            [jnp.max(jnp.abs(g._data.astype(jnp.float32))) for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g._data.astype(jnp.float32)) ** norm_type)
             for g in grads])) ** (1.0 / norm_type)
    if error_if_nonfinite and not bool(jnp.isfinite(total)):
        raise RuntimeError(
            f"grad norm is non-finite ({float(total)}); set "
            "error_if_nonfinite=False to clip anyway")
    scale = jnp.minimum(1.0, max_norm / (total + 1e-6))
    for p in params:
        if p.grad is not None:
            p.grad._data = (p.grad._data.astype(jnp.float32)
                            * scale).astype(p.grad._data.dtype)
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    """In-place elementwise grad clamp (reference clip_grad_value_.py)."""
    params = [parameters] if isinstance(parameters, Tensor) else \
        list(parameters)
    cv = abs(float(clip_value))
    for p in params:
        if p.grad is not None:
            p.grad._data = jnp.clip(p.grad._data, -cv, cv)


def parameters_to_vector(parameters, name=None) -> Tensor:
    """Flatten+concat parameters (reference transform_parameters.py)."""
    return Tensor(jnp.concatenate(
        [p._data.reshape(-1) for p in parameters]))


def vector_to_parameters(vec, parameters, name=None):
    """Scatter a flat vector back into the parameter list in place."""
    arr = vec._data if isinstance(vec, Tensor) else jnp.asarray(vec)
    off = 0
    for p in parameters:
        n = int(np.prod(p.shape)) if len(p.shape) else 1
        p._data = arr[off:off + n].reshape(p._data.shape).astype(
            p._data.dtype)
        off += n
    if off != arr.shape[0]:
        raise ValueError(
            f"vector has {arr.shape[0]} elements but parameters hold {off}")


# ---------------------------------------------------------------------------
# Weight norm: w = g * v / ||v||  (reference weight_norm_hook.py — swaps the
# weight for (weight_g, weight_v) and recomputes w in a forward pre-hook).
# ---------------------------------------------------------------------------

def _norm_except(v, dim):
    axes = tuple(i for i in range(v.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(v.astype(jnp.float32) ** 2, axis=axes,
                            keepdims=True))


def weight_norm(layer, name="weight", dim=0):
    if getattr(layer, f"_{name}_norm_hook", None) is not None:
        raise RuntimeError(f"weight_norm already applied to {name!r}")
    w = getattr(layer, name)
    if dim is None:
        dim = -1  # treat whole tensor as one group
    v0 = w._data
    g0 = _norm_except(v0, dim) if dim >= 0 else \
        jnp.sqrt(jnp.sum(v0.astype(jnp.float32) ** 2)).reshape(
            (1,) * v0.ndim)
    weight_v = Parameter(v0)
    weight_g = Parameter(g0.astype(v0.dtype))
    del layer._parameters[name]
    layer.add_parameter(name + "_v", weight_v)
    layer.add_parameter(name + "_g", weight_g)

    def recompute(lyr, inputs):
        v = getattr(lyr, name + "_v")
        g = getattr(lyr, name + "_g")
        if dim >= 0:
            norm = (v.astype("float32") ** 2).sum(
                axis=[i for i in range(len(v.shape)) if i != dim],
                keepdim=True).sqrt()
        else:
            norm = (v.astype("float32") ** 2).sum().sqrt()
        w = g.astype("float32") * v.astype("float32") / (norm + 1e-12)
        setattr(lyr, name, w.astype(str(v.dtype).split(".")[-1]))
        return None

    handle = layer.register_forward_pre_hook(recompute)
    setattr(layer, f"_{name}_norm_hook", handle)
    recompute(layer, None)          # materialize w for direct access
    return layer


def remove_weight_norm(layer, name="weight"):
    handle = getattr(layer, f"_{name}_norm_hook", None)
    if handle is None:
        raise ValueError(f"no weight_norm on {name!r}")
    handle.remove()
    setattr(layer, f"_{name}_norm_hook", None)
    w = getattr(layer, name)
    data = w._data if isinstance(w, Tensor) else jnp.asarray(w)
    del layer._parameters[name + "_v"]
    del layer._parameters[name + "_g"]
    layer.add_parameter(name, Parameter(data))
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """Divide the weight by its largest singular value, estimated by power
    iteration refreshed each forward (reference spectral_norm_hook.py)."""
    w = getattr(layer, name)
    if dim is None:
        dim = 0
    mat0 = np.moveaxis(np.asarray(w.numpy(), np.float32), dim, 0)
    mat0 = mat0.reshape(mat0.shape[0], -1)
    rng = np.random.RandomState(0)
    state = {
        "u": jnp.asarray(rng.randn(mat0.shape[0]), jnp.float32),
        "v": jnp.asarray(rng.randn(mat0.shape[1]), jnp.float32),
    }

    def normalize(x):
        return x / (jnp.linalg.norm(x) + eps)

    def hook(lyr, inputs):
        wt = getattr(lyr, name + "_orig")
        mat = jnp.moveaxis(wt._data.astype(jnp.float32), dim, 0)
        mat = mat.reshape(mat.shape[0], -1)
        u, v = state["u"], state["v"]
        for _ in range(n_power_iterations):
            v = normalize(mat.T @ u)
            u = normalize(mat @ v)
        state["u"], state["v"] = u, v
        sigma = u @ mat @ v
        setattr(lyr, name,
                Tensor((wt._data.astype(jnp.float32) / sigma).astype(
                    wt._data.dtype)))
        return None

    orig = Parameter(w._data)
    del layer._parameters[name]
    layer.add_parameter(name + "_orig", orig)
    handle = layer.register_forward_pre_hook(hook)
    setattr(layer, f"_{name}_spectral_hook", handle)
    hook(layer, None)
    return layer
