"""Remaining layer surface (reference python/paddle/nn/layer/
{common,pooling,loss,container,rnn}.py entries not covered elsewhere).
"""
from __future__ import annotations

from .. import functional as F
from .layers import Layer

__all__ = [
    "FeatureAlphaDropout", "Softmax2D", "ParameterDict", "RNNTLoss",
    "HSigmoidLoss", "MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D",
    "AdaptiveLogSoftmaxWithLoss", "Unflatten", "FractionalMaxPool2D",
    "FractionalMaxPool3D", "ZeroPad1D", "ZeroPad3D", "BeamSearchDecoder",
    "dynamic_decode",
]


class FeatureAlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.feature_alpha_dropout(x, self.p, training=self.training)


class Softmax2D(Layer):
    """Softmax over channels of [N, C, H, W] (reference common.py
    Softmax2D)."""

    def forward(self, x):
        if len(x.shape) != 4:
            raise ValueError(f"Softmax2D expects 4-D NCHW, got {x.shape}")
        return F.softmax(x, axis=1)


class ParameterDict(Layer):
    """Keyed parameter container (reference container.py ParameterDict)."""

    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            self.update(parameters)

    def update(self, parameters):
        items = parameters.items() if hasattr(parameters, "items") \
            else parameters
        for k, v in items:
            self.add_parameter(str(k), v)

    def __getitem__(self, key):
        return self._parameters[str(key)]

    def __setitem__(self, key, value):
        self.add_parameter(str(key), value)

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters)

    def keys(self):
        return self._parameters.keys()

    def values(self):
        return self._parameters.values()

    def items(self):
        return self._parameters.items()


class RNNTLoss(Layer):
    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, input, label, input_lengths, label_lengths):
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           blank=self.blank, reduction=self.reduction)


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid loss layer (reference loss.py HSigmoidLoss)."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        from ..initializer import Normal
        from ..initializer.attr import ParamAttr
        self.num_classes = num_classes
        c = num_classes - 1
        self.weight = self.create_parameter(
            [c, feature_size], attr=ParamAttr._to_attr(weight_attr),
            default_initializer=Normal(0.0, 1.0 / feature_size ** 0.5))
        self.bias = (None if bias_attr is False else self.create_parameter(
            [c, 1], attr=ParamAttr._to_attr(bias_attr), is_bias=True))

    def forward(self, input, label, path_table=None, path_code=None):
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               self.bias, path_table, path_code)


class _UnpoolNd(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format=None,
                 output_size=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.output_size = output_size
        self.data_format = data_format


class MaxUnPool1D(_UnpoolNd):
    def forward(self, x, indices):
        return F.max_unpool1d(x, indices, self.kernel_size, self.stride,
                              self.padding,
                              output_size=self.output_size)


class MaxUnPool2D(_UnpoolNd):
    def forward(self, x, indices):
        return F.max_unpool2d(x, indices, self.kernel_size, self.stride,
                              self.padding,
                              output_size=self.output_size)


class MaxUnPool3D(_UnpoolNd):
    def forward(self, x, indices):
        return F.max_unpool3d(x, indices, self.kernel_size, self.stride,
                              self.padding,
                              output_size=self.output_size)


class FractionalMaxPool2D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.random_u = random_u
        self.return_mask = return_mask

    def forward(self, x):
        return F.fractional_max_pool2d(x, self.output_size,
                                       random_u=self.random_u,
                                       return_mask=self.return_mask)


class FractionalMaxPool3D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.random_u = random_u
        self.return_mask = return_mask

    def forward(self, x):
        return F.fractional_max_pool3d(x, self.output_size,
                                       random_u=self.random_u,
                                       return_mask=self.return_mask)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """Adaptive softmax layer (reference loss.py
    AdaptiveLogSoftmaxWithLoss)."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, name=None):
        super().__init__()
        cutoffs = list(cutoffs)
        if cutoffs != sorted(cutoffs) or cutoffs[-1] > n_classes:
            raise ValueError(f"bad cutoffs {cutoffs}")
        self.cutoffs = cutoffs + [n_classes]
        self.n_clusters = len(self.cutoffs) - 1
        head_size = self.cutoffs[0] + self.n_clusters
        self.head_weight = self.create_parameter([in_features, head_size])
        self.head_bias = (self.create_parameter([head_size], is_bias=True)
                          if head_bias else None)
        self.tail_weights = []
        for i in range(self.n_clusters):
            hsz = max(1, int(in_features / (div_value ** (i + 1))))
            osz = self.cutoffs[i + 1] - self.cutoffs[i]
            w1 = self.create_parameter([in_features, hsz])
            w2 = self.create_parameter([hsz, osz])
            self.add_parameter(f"tail_{i}_0", w1)
            self.add_parameter(f"tail_{i}_1", w2)
            self.tail_weights.append([w1, w2])

    def forward(self, input, label):
        return F.adaptive_log_softmax_with_loss(
            input, label, self.head_weight, self.tail_weights, self.cutoffs,
            self.head_bias)

    def log_prob(self, input):
        import jax
        import jax.numpy as jnp

        from ...core.tensor import Tensor
        x = input._data.astype(jnp.float32)
        head = x @ self.head_weight._data.astype(jnp.float32)
        if self.head_bias is not None:
            head = head + self.head_bias._data.astype(jnp.float32)
        head_lsm = jax.nn.log_softmax(head, axis=-1)
        outs = [head_lsm[..., :self.cutoffs[0]]]
        for i in range(self.n_clusters):
            w1, w2 = self.tail_weights[i]
            proj = (x @ w1._data.astype(jnp.float32)) \
                @ w2._data.astype(jnp.float32)
            tail_lsm = jax.nn.log_softmax(proj, axis=-1)
            outs.append(tail_lsm
                        + head_lsm[..., self.cutoffs[0] + i][..., None])
        return Tensor(jnp.concatenate(outs, axis=-1))

    def predict(self, input):
        return self.log_prob(input).argmax(axis=-1)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis = axis
        self.shape = shape

    def forward(self, x):
        from ...ops.manipulation import unflatten
        return unflatten(x, self.axis, self.shape)


class ZeroPad1D(Layer):
    def __init__(self, padding, data_format="NCL", name=None):
        super().__init__()
        self.padding = padding
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, "constant", 0.0, self.data_format)


class ZeroPad3D(Layer):
    def __init__(self, padding, data_format="NCDHW", name=None):
        super().__init__()
        self.padding = padding
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, "constant", 0.0, self.data_format)


# ---------------------------------------------------------------------------
# Seq2seq decoding (reference nn/decode.py BeamSearchDecoder +
# dynamic_decode).  Eager loop over the decoder cell; beams tracked with
# gather_tree for final sequence reconstruction.
# ---------------------------------------------------------------------------

class BeamSearchDecoder:
    """(reference nn/decode.py:BeamSearchDecoder) — wraps an RNN cell +
    embedding/output functions for beam search via dynamic_decode."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn


def dynamic_decode(decoder, inits=None, max_step_num=32, **kwargs):
    """Greedy-within-beam decode loop (reference nn/decode.py
    dynamic_decode).  Returns (ids [B, T, beam], final_states).
    """
    import jax
    import jax.numpy as jnp

    from ...core.tensor import Tensor
    from ...ops.misc import gather_tree

    cell = decoder.cell
    beam = decoder.beam_size
    state = inits
    # infer batch from the initial state tree
    leaves = [state] if isinstance(state, Tensor) else list(
        state if isinstance(state, (list, tuple)) else [state])
    B = leaves[0].shape[0]

    # tile states across beams: [B, ...] -> [B*beam, ...]
    def tile(t):
        arr = t._data if isinstance(t, Tensor) else jnp.asarray(t)
        return Tensor(jnp.repeat(arr, beam, axis=0))

    state = [tile(s) for s in leaves]
    tok = Tensor(jnp.full((B * beam,), decoder.start_token, jnp.int32))
    log_probs = jnp.where(
        jnp.arange(B * beam) % beam == 0, 0.0, -1e9)    # only beam 0 live
    step_ids, step_parents = [], []
    finished = jnp.zeros((B * beam,), bool)

    for t in range(max_step_num):
        emb = decoder.embedding_fn(tok) if decoder.embedding_fn else tok
        out, new_state = cell(emb, state)    # states contract: a list
        logits = decoder.output_fn(out) if decoder.output_fn else out
        larr = logits._data if isinstance(logits, Tensor) \
            else jnp.asarray(logits)
        lsm = jax.nn.log_softmax(larr.astype(jnp.float32), axis=-1)
        V = lsm.shape[-1]
        # frozen beams only extend with end_token at no cost
        frozen = jnp.full((B * beam, V), -1e9).at[:, decoder.end_token].set(0.0)
        lsm = jnp.where(finished[:, None], frozen, lsm)
        total = log_probs[:, None] + lsm                # [B*beam, V]
        total = total.reshape(B, beam * V)
        top_v, top_i = jax.lax.top_k(total, beam)
        parent = top_i // V                             # beam index in 0..beam
        sym = top_i % V
        # flatten back to [B*beam]
        gather = (jnp.arange(B)[:, None] * beam + parent).reshape(-1)
        log_probs = top_v.reshape(-1)
        tok = Tensor(sym.reshape(-1).astype(jnp.int32))
        state = [Tensor(jnp.take(s._data, gather, axis=0))
                 for s in (new_state if isinstance(new_state, (list, tuple))
                           else [new_state])]
        finished = jnp.take(finished, gather) | (
            sym.reshape(-1) == decoder.end_token)
        step_ids.append(sym)
        step_parents.append(parent)
        if bool(finished.all()):
            break

    ids = Tensor(jnp.stack(step_ids).astype(jnp.int64))       # [T, B, beam]
    parents = Tensor(jnp.stack(step_parents).astype(jnp.int64))
    seqs = gather_tree(ids, parents)
    return seqs, state
