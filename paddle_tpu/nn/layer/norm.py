"""Normalization layers.

Parity with /root/reference/python/paddle/nn/layer/norm.py (+RMSNorm from
incubate fused_rms_norm).
"""
from __future__ import annotations

import numpy as np

from ...core.tensor import Tensor
from .. import functional as F
from ..initializer import Constant
from ..initializer.attr import ParamAttr
from .layers import Layer

__all__ = ["LayerNorm", "RMSNorm", "BatchNorm", "BatchNorm1D", "BatchNorm2D",
           "BatchNorm3D", "SyncBatchNorm", "InstanceNorm1D", "InstanceNorm2D",
           "InstanceNorm3D", "GroupNorm", "LocalResponseNorm", "SpectralNorm"]


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = (None if weight_attr is False else self.create_parameter(
            self._normalized_shape, attr=ParamAttr._to_attr(weight_attr),
            default_initializer=Constant(1.0)))
        self.bias = (None if bias_attr is False else self.create_parameter(
            self._normalized_shape, attr=ParamAttr._to_attr(bias_attr), is_bias=True))

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}, epsilon={self._epsilon}"


class RMSNorm(Layer):
    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            [hidden_size], attr=ParamAttr._to_attr(weight_attr),
            default_initializer=Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = (None if weight_attr is False else self.create_parameter(
            [num_features], attr=ParamAttr._to_attr(weight_attr),
            default_initializer=Constant(1.0)))
        self.bias = (None if bias_attr is False else self.create_parameter(
            [num_features], attr=ParamAttr._to_attr(bias_attr), is_bias=True))
        import jax.numpy as jnp
        self.register_buffer("_mean", Tensor(jnp.zeros([num_features], jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones([num_features], jnp.float32)))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight, self.bias,
                            training=self.training, momentum=self._momentum,
                            epsilon=self._epsilon, data_format=self._data_format,
                            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format="NCL", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         data_format, use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format="NCDHW", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN.  Under pjit/shard_map batch stats are computed over
    the global batch automatically; eager single-process uses local stats."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            out = SyncBatchNorm(layer._num_features, layer._momentum, layer._epsilon,
                                data_format=layer._data_format)
            out.weight = layer.weight
            out.bias = layer.bias
            out._mean = layer._mean
            out._variance = layer._variance
        for name, sub in list(layer._sub_layers.items()):
            out._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return out


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = (None if weight_attr is False else self.create_parameter(
            [num_features], attr=ParamAttr._to_attr(weight_attr),
            default_initializer=Constant(1.0)))
        self.bias = (None if bias_attr is False else self.create_parameter(
            [num_features], attr=ParamAttr._to_attr(bias_attr), is_bias=True))

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon, data_format=self._data_format)


class InstanceNorm1D(_InstanceNormBase):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCL", name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr, bias_attr,
                         data_format)


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCDHW", name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr, bias_attr,
                         data_format)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = (None if weight_attr is False else self.create_parameter(
            [num_channels], attr=ParamAttr._to_attr(weight_attr),
            default_initializer=Constant(1.0)))
        self.bias = (None if bias_attr is False else self.create_parameter(
            [num_channels], attr=ParamAttr._to_attr(bias_attr), is_bias=True))

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=0.0001, beta=0.75, k=1.0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._epsilon = epsilon
        import jax.numpy as jnp
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        from ...core import random_state
        import jax
        self.weight_u = Tensor(jax.random.normal(random_state.next_key(), (h,), jnp.float32))
        self.weight_v = Tensor(jax.random.normal(random_state.next_key(), (w,), jnp.float32))

    def forward(self, weight):
        from ...ops import manipulation as M
        from ...ops import math as mm
        from ...ops.linalg import norm as _vnorm
        w = weight
        if self._dim != 0:
            perm = [self._dim] + [i for i in range(w.ndim) if i != self._dim]
            w = M.transpose(w, perm)
        h = w.shape[0]
        w_mat = M.reshape(w, [h, -1])
        u, v = self.weight_u, self.weight_v
        for _ in range(self._power_iters):
            v_new = mm.matmul(w_mat, u, transpose_x=True)
            v = v_new / (_vnorm(v_new) + self._epsilon)
            u_new = mm.matmul(w_mat, v)
            u = u_new / (_vnorm(u_new) + self._epsilon)
        self.weight_u._data = u.detach()._data
        self.weight_v._data = v.detach()._data
        sigma = mm.matmul(M.reshape(u, [1, -1]), mm.matmul(w_mat, M.reshape(v, [-1, 1])))
        return weight / M.reshape(sigma, [])
