"""Activation layers.  Parity with /root/reference/python/paddle/nn/layer/activation.py."""
from __future__ import annotations

from .. import functional as F
from ..initializer import Constant
from ..initializer.attr import ParamAttr
from .layers import Layer

__all__ = ["CELU", "ELU", "GELU", "GLU", "Hardshrink", "Hardsigmoid", "Hardswish",
           "Hardtanh", "LeakyReLU", "LogSigmoid", "LogSoftmax", "Maxout", "Mish",
           "PReLU", "ReLU", "ReLU6", "RReLU", "SELU", "Sigmoid", "Silu",
           "Softmax", "Softplus", "Softshrink", "Softsign", "Swish", "Tanh",
           "Tanhshrink", "ThresholdedReLU"]


def _mk(name, fn, params=()):
    def __init__(self, *args, **kwargs):
        Layer.__init__(self)
        self._args = args
        self._kwargs = {k: v for k, v in kwargs.items() if k != "name"}

    def forward(self, x):
        return fn(x, *self._args, **self._kwargs)

    return type(name, (Layer,), {"__init__": __init__, "forward": forward})


CELU = _mk("CELU", F.celu)
ELU = _mk("ELU", F.elu)
GELU = _mk("GELU", F.gelu)
GLU = _mk("GLU", F.glu)
Hardshrink = _mk("Hardshrink", F.hardshrink)
Hardsigmoid = _mk("Hardsigmoid", F.hardsigmoid)
Hardswish = _mk("Hardswish", F.hardswish)
Hardtanh = _mk("Hardtanh", F.hardtanh)
LeakyReLU = _mk("LeakyReLU", F.leaky_relu)
LogSigmoid = _mk("LogSigmoid", F.log_sigmoid)
LogSoftmax = _mk("LogSoftmax", F.log_softmax)
Maxout = _mk("Maxout", F.maxout)
Mish = _mk("Mish", F.mish)
ReLU = _mk("ReLU", F.relu)
ReLU6 = _mk("ReLU6", F.relu6)
SELU = _mk("SELU", F.selu)
Sigmoid = _mk("Sigmoid", F.sigmoid)
Silu = _mk("Silu", F.silu)
Softmax = _mk("Softmax", F.softmax)
Softplus = _mk("Softplus", F.softplus)
Softshrink = _mk("Softshrink", F.softshrink)
Softsign = _mk("Softsign", F.softsign)
Swish = _mk("Swish", F.swish)
Tanh = _mk("Tanh", F.tanh)
Tanhshrink = _mk("Tanhshrink", F.tanhshrink)
ThresholdedReLU = _mk("ThresholdedReLU", F.thresholded_relu)


class RReLU(Layer):
    def __init__(self, lower=0.125, upper=0.3333333, name=None):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=ParamAttr._to_attr(weight_attr),
            default_initializer=Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)
