"""Recurrent layers: SimpleRNN/LSTM/GRU (+cells, RNN/BiRNN wrappers).

Capability parity with /root/reference/python/paddle/nn/layer/rnn.py
(SimpleRNNCell :742, LSTMCell :919, GRUCell :1145, RNN :1340, BiRNN :1422,
RNNBase :1515, SimpleRNN :1860, LSTM :1983, GRU :2120).

TPU-native design: the built-in SimpleRNN/LSTM/GRU run the ENTIRE time loop
as one dispatched ``lax.scan`` per layer-direction (a single compiled XLA
program — the analog of the reference's fused cuDNN rnn kernel path), not a
Python step loop.  The generic RNN/BiRNN wrappers run arbitrary user cells
step-by-step in eager mode, matching the reference's non-cuDNN fallback.

Gate math (matches the reference docstrings exactly):
  SimpleRNN: h = act(x W_ih^T + b_ih + h W_hh^T + b_hh)
  LSTM gates [i, f, g, o] stacked in 4H; c = f*c + i*tanh(g); h = o*tanh(c)
  GRU gates [r, z, c] stacked in 3H; h' = z*h + (1-z)*tanh(x_c + r*(h_c))
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ...core import dispatch as D
from ...core.tensor import Tensor
from .container import LayerList
from .layers import Layer

__all__ = ["RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell",
           "RNN", "BiRNN", "SimpleRNN", "LSTM", "GRU"]


def _act(name):
    return {"tanh": jnp.tanh, "relu": jax.nn.relu}[name]


# ---------------------------------------------------------------------------
# Cells (single step, eager ops — reference RNNCellBase surface)
# ---------------------------------------------------------------------------

class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        batch = batch_ref.shape[batch_dim_idx]
        shapes = shape if shape is not None else self.state_shape
        if isinstance(shapes[0], (tuple, list)):
            return tuple(
                Tensor(jnp.full((batch,) + tuple(s), init_value, jnp.float32))
                for s in shapes)
        return Tensor(jnp.full((batch,) + tuple(shapes), init_value,
                               jnp.float32))


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        if hidden_size <= 0:
            raise ValueError("hidden_size must be positive")
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        from ..initializer import Uniform
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            (hidden_size, input_size), weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            (hidden_size, hidden_size), weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            (hidden_size,), bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            (hidden_size,), bias_hh_attr, is_bias=True,
            default_initializer=init)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h = D.apply(
            "simple_rnn_cell", _simple_rnn_cell_impl,
            (inputs, states, self.weight_ih, self.weight_hh, self.bias_ih,
             self.bias_hh),
            {"activation": self.activation})
        return h, h


def _simple_rnn_cell_impl(x, h, w_ih, w_hh, b_ih, b_hh, activation):
    return _act(activation)(x @ w_ih.T + b_ih + h @ w_hh.T + b_hh)


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 proj_size=0, name=None):
        super().__init__()
        if hidden_size <= 0:
            raise ValueError("hidden_size must be positive")
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.proj_size = proj_size
        std = 1.0 / math.sqrt(hidden_size)
        from ..initializer import Uniform
        init = Uniform(-std, std)
        h_in = proj_size if proj_size > 0 else hidden_size
        self.weight_ih = self.create_parameter(
            (4 * hidden_size, input_size), weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            (4 * hidden_size, h_in), weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            (4 * hidden_size,), bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            (4 * hidden_size,), bias_hh_attr, is_bias=True,
            default_initializer=init)
        if proj_size > 0:
            self.weight_ho = self.create_parameter(
                (hidden_size, proj_size), weight_hh_attr,
                default_initializer=init)

    @property
    def state_shape(self):
        return ((self.proj_size or self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs, self.state_shape)
        h_prev, c_prev = states
        args = (inputs, h_prev, c_prev, self.weight_ih, self.weight_hh,
                self.bias_ih, self.bias_hh)
        if self.proj_size > 0:
            h, c = D.apply("lstm_cell_proj", _lstm_cell_impl,
                           args + (self.weight_ho,), {"proj": True})
        else:
            h, c = D.apply("lstm_cell", _lstm_cell_impl, args,
                           {"proj": False})
        return h, (h, c)


def _lstm_cell_impl(x, h, c, w_ih, w_hh, b_ih, b_hh, *rest, proj=False):
    gates = x @ w_ih.T + b_ih + h @ w_hh.T + b_hh
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    c_new = f * c + i * jnp.tanh(g)
    h_new = o * jnp.tanh(c_new)
    if proj:
        h_new = h_new @ rest[0]
    return h_new, c_new


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        if hidden_size <= 0:
            raise ValueError("hidden_size must be positive")
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        from ..initializer import Uniform
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            (3 * hidden_size, input_size), weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            (3 * hidden_size, hidden_size), weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            (3 * hidden_size,), bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            (3 * hidden_size,), bias_hh_attr, is_bias=True,
            default_initializer=init)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h = D.apply(
            "gru_cell", _gru_cell_impl,
            (inputs, states, self.weight_ih, self.weight_hh, self.bias_ih,
             self.bias_hh), {})
        return h, h


def _gru_cell_impl(x, h, w_ih, w_hh, b_ih, b_hh):
    xg = x @ w_ih.T + b_ih
    hg = h @ w_hh.T + b_hh
    xr, xz, xc = jnp.split(xg, 3, axis=-1)
    hr, hz, hc = jnp.split(hg, 3, axis=-1)
    r = jax.nn.sigmoid(xr + hr)
    z = jax.nn.sigmoid(xz + hz)
    c = jnp.tanh(xc + r * hc)
    return z * h + (1.0 - z) * c


# ---------------------------------------------------------------------------
# Generic wrappers over arbitrary cells (reference RNN :1340, BiRNN :1422)
# ---------------------------------------------------------------------------

class RNN(Layer):
    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        from ... import ops
        axis = 0 if self.time_major else 1
        T = inputs.shape[axis]
        steps = range(T - 1, -1, -1) if self.is_reverse else range(T)
        states = initial_states
        outs = [None] * T
        for t in steps:
            x_t = inputs[:, t] if axis == 1 else inputs[t]
            y, states = self.cell(x_t, states, **kwargs)
            outs[t] = y
        out = ops.PUBLIC_OPS["stack"](outs, axis=axis)
        if sequence_length is not None:
            mask = _length_mask(sequence_length, T, out.dtype.name)
            mask = mask.T if self.time_major else mask     # align time axis
            m = mask.unsqueeze(-1) if hasattr(mask, "unsqueeze") else mask
            out = out * m
        return out, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        from ... import ops
        s_fw, s_bw = (initial_states if initial_states is not None
                      else (None, None))
        o_fw, s_fw = self.rnn_fw(inputs, s_fw, sequence_length, **kwargs)
        o_bw, s_bw = self.rnn_bw(inputs, s_bw, sequence_length, **kwargs)
        out = ops.PUBLIC_OPS["concat"]([o_fw, o_bw], axis=-1)
        return out, (s_fw, s_bw)


def _length_mask(sequence_length, T, dtype_name):
    from ... import ops
    sl = sequence_length
    arr = sl._data if isinstance(sl, Tensor) else jnp.asarray(sl)
    mask = (jnp.arange(T)[None, :] < arr[:, None]).astype(dtype_name)
    return Tensor(mask)


# ---------------------------------------------------------------------------
# Fused multi-layer RNNs: one lax.scan per layer-direction
# (reference RNNBase :1515 — the cuDNN-fused path re-designed for XLA)
# ---------------------------------------------------------------------------

_MODES = {
    "RNN_TANH": (1, "simple"),
    "RNN_RELU": (1, "simple"),
    "LSTM": (4, "lstm"),
    "GRU": (3, "gru"),
}


def _scan_rnn_impl(*args, mode, reverse, has_len, time_major,
                   act="tanh"):
    """One layer-direction over the full sequence: a single lax.scan.
    args: x [B,T,I] (batch-major inside), h0 [B,H] (+c0), w_ih, w_hh, b_ih,
    b_hh (+seq_len [B])."""
    if mode == "lstm":
        x, h0, c0, w_ih, w_hh, b_ih, b_hh = args[:7]
        rest = args[7:]
    else:
        x, h0, w_ih, w_hh, b_ih, b_hh = args[:6]
        c0, rest = None, args[6:]
    seq_len = rest[0] if has_len else None
    xt = jnp.swapaxes(x, 0, 1) if not time_major else x   # [T,B,I]
    T = xt.shape[0]
    tidx = jnp.arange(T)
    if reverse:
        xt = xt[::-1]
        tidx = tidx[::-1]

    def step(carry, inp):
        x_t, t = inp
        if mode == "lstm":
            h, c = carry
            h2, c2 = _lstm_cell_impl(x_t, h, c, w_ih, w_hh, b_ih, b_hh)
        elif mode == "gru":
            h = carry
            h2 = _gru_cell_impl(x_t, h, w_ih, w_hh, b_ih, b_hh)
            c = c2 = None
        else:
            h = carry
            h2 = _simple_rnn_cell_impl(x_t, h, w_ih, w_hh, b_ih, b_hh,
                                       act)
            c = c2 = None
        if seq_len is not None:
            valid = (t < seq_len)[:, None]
            h2 = jnp.where(valid, h2, h)
            if mode == "lstm":
                c2 = jnp.where(valid, c2, c)
            out = jnp.where(valid, h2, jnp.zeros((), h2.dtype))
        else:
            out = h2
        new_carry = (h2, c2) if mode == "lstm" else h2
        return new_carry, out

    carry0 = (h0, c0) if mode == "lstm" else h0
    carry, outs = lax.scan(step, carry0, (xt, tidx))
    if reverse:
        outs = outs[::-1]
    outs = jnp.swapaxes(outs, 0, 1) if not time_major else outs
    if mode == "lstm":
        h_f, c_f = carry
        return outs, h_f, c_f
    return outs, carry


class RNNBase(LayerList):
    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, proj_size=0):
        super().__init__()
        if direction in ("bidirectional", "bidirect"):
            self.num_directions = 2
        elif direction == "forward":
            self.num_directions = 1
        else:
            raise ValueError(f"unknown direction {direction!r}")
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.activation = activation
        gates, self.kind = _MODES[mode]
        std = 1.0 / math.sqrt(hidden_size)
        from ..initializer import Uniform
        init = Uniform(-std, std)
        for layer in range(num_layers):
            for d in range(self.num_directions):
                in_sz = (input_size if layer == 0
                         else hidden_size * self.num_directions)
                suffix = "_reverse" if d == 1 else ""
                self.add_parameter(
                    f"weight_ih_l{layer}{suffix}",
                    self.create_parameter((gates * hidden_size, in_sz),
                                          weight_ih_attr,
                                          default_initializer=init))
                self.add_parameter(
                    f"weight_hh_l{layer}{suffix}",
                    self.create_parameter((gates * hidden_size, hidden_size),
                                          weight_hh_attr,
                                          default_initializer=init))
                self.add_parameter(
                    f"bias_ih_l{layer}{suffix}",
                    self.create_parameter((gates * hidden_size,),
                                          bias_ih_attr, is_bias=True,
                                          default_initializer=init))
                self.add_parameter(
                    f"bias_hh_l{layer}{suffix}",
                    self.create_parameter((gates * hidden_size,),
                                          bias_hh_attr, is_bias=True,
                                          default_initializer=init))

    def _weights(self, layer, d):
        sfx = "_reverse" if d == 1 else ""
        return (getattr(self, f"weight_ih_l{layer}{sfx}"),
                getattr(self, f"weight_hh_l{layer}{sfx}"),
                getattr(self, f"bias_ih_l{layer}{sfx}"),
                getattr(self, f"bias_hh_l{layer}{sfx}"))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ... import ops
        ND = self.num_directions
        batch_axis = 1 if self.time_major else 0
        B = inputs.shape[batch_axis]
        H = self.hidden_size
        if initial_states is None:
            z = Tensor(jnp.zeros((self.num_layers * ND, B, H), jnp.float32))
            initial_states = (z, Tensor(z._data.copy())) \
                if self.kind == "lstm" else z

        kind = self.kind
        mode_is_lstm = kind == "lstm"
        if mode_is_lstm:
            h0_all, c0_all = initial_states
        else:
            h0_all, c0_all = initial_states, None

        x = inputs
        final_h, final_c = [], []
        for layer in range(self.num_layers):
            outs_d = []
            for d in range(ND):
                idx = layer * ND + d
                w_ih, w_hh, b_ih, b_hh = self._weights(layer, d)
                h0 = h0_all[idx]
                args = [x, h0]
                if mode_is_lstm:
                    args.append(c0_all[idx])
                args += [w_ih, w_hh, b_ih, b_hh]
                if sequence_length is not None:
                    args.append(sequence_length)
                attrs = {"mode": kind, "reverse": d == 1,
                         "has_len": sequence_length is not None,
                         "time_major": self.time_major,
                         "act": self.activation}
                if mode_is_lstm:
                    out, h_f, c_f = D.apply(f"fused_{kind}_scan",
                                            _scan_rnn_impl, tuple(args),
                                            attrs)
                    final_c.append(c_f)
                else:
                    out, h_f = D.apply(f"fused_{kind}_scan", _scan_rnn_impl,
                                       tuple(args), attrs)
                final_h.append(h_f)
                outs_d.append(out)
            x = (outs_d[0] if ND == 1
                 else ops.PUBLIC_OPS["concat"](outs_d, axis=-1))
            if self.dropout and self.training and layer < self.num_layers - 1:
                from .. import functional as F
                x = F.dropout(x, p=self.dropout)
        h_n = ops.PUBLIC_OPS["stack"](final_h, axis=0)
        if mode_is_lstm:
            c_n = ops.PUBLIC_OPS["stack"](final_c, axis=0)
            return x, (h_n, c_n)
        return x, h_n


class SimpleRNN(RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kwargs):
        mode = "RNN_RELU" if activation == "relu" else "RNN_TANH"
        super().__init__(mode, input_size, hidden_size, num_layers,
                         direction, time_major, dropout, activation, **kwargs)


class LSTM(RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 **kwargs):
        kwargs.pop("proj_size", None)
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kwargs)


class GRU(RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 **kwargs):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kwargs)
