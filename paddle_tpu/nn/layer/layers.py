"""nn.Layer base class.

Capability parity with the reference Layer
(/root/reference/python/paddle/nn/layer/layers.py:353): parameter/buffer/
sublayer registries, hooks, state_dict round-trip, train/eval, to(), apply().
"""
from __future__ import annotations

import collections
from typing import Callable, Iterator

import numpy as np
import jax
import jax.numpy as jnp

from ...core.dtype import convert_dtype
from ...core.tensor import Parameter, Tensor

__all__ = ["Layer"]


class HookRemoveHelper:
    def __init__(self, hooks, key):
        self._hooks = hooks
        self._key = key

    def remove(self):
        self._hooks.pop(self._key, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = convert_dtype(dtype)
        self._parameters: dict[str, Parameter] = collections.OrderedDict()
        self._buffers: dict[str, Tensor] = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._sub_layers: dict[str, "Layer"] = collections.OrderedDict()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._hook_id = 0
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # ---- construction helpers ----
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        from ..initializer import Constant, XavierNormal
        from ..initializer.attr import ParamAttr

        dtype = convert_dtype(dtype or self._dtype)
        init = default_initializer
        name = None
        learning_rate = 1.0
        trainable = True
        regularizer = None
        if isinstance(attr, ParamAttr):
            init = attr.initializer or init
            name = attr.name
            learning_rate = attr.learning_rate
            trainable = attr.trainable
            regularizer = attr.regularizer
        elif attr is False:
            return None
        if init is None:
            init = Constant(0.0) if is_bias else XavierNormal()
        data = init(shape, dtype)
        p = Parameter(data, name=name, trainable=trainable)
        p.optimize_attr = {"learning_rate": learning_rate}
        p.regularizer = regularizer
        p.is_bias = is_bias
        return p

    def create_tensor(self, name=None, persistable=False, dtype=None):
        t = Tensor(jnp.zeros((), convert_dtype(dtype or self._dtype).np_dtype), name=name)
        t.persistable = persistable
        return t

    def add_parameter(self, name, parameter):
        if parameter is None:
            self._parameters[name] = None
        else:
            self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # ---- attribute protocol ----
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            params[name] = value
            if buffers is not None:
                buffers.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            layers[name] = value
        elif params is not None and name in params:
            params[name] = value
        elif layers is not None and name in layers:
            layers[name] = value
        elif buffers is not None and name in buffers:
            buffers[name] = value
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        extra = []
        for store in ("_parameters", "_buffers", "_sub_layers"):
            extra += list(self.__dict__.get(store, ()))
        return list(super().__dir__()) + extra

    # ---- traversal ----
    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True,
                         include_self=True, remove_duplicate=True):
        seen = set()
        for layer_prefix, layer in self.named_sublayers(prefix=prefix, include_self=True):
            if not include_sublayers and layer is not self:
                continue
            for name, p in layer._parameters.items():
                if p is None or (remove_duplicate and id(p) in seen):
                    continue
                seen.add(id(p))
                yield (f"{layer_prefix}.{name}" if layer_prefix else name, p)

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for layer_prefix, layer in self.named_sublayers(prefix=prefix, include_self=True):
            if not include_sublayers and layer is not self:
                continue
            for name, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{layer_prefix}.{name}" if layer_prefix else name, b)

    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self):
        seen = set()
        for name, l in self._sub_layers.items():
            if l is not None and id(l) not in seen:
                seen.add(id(l))
                yield name, l

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        if layers_set is None:
            layers_set = set()
        if include_self or prefix == "":
            if id(self) not in layers_set:
                layers_set.add(id(self))
                yield prefix, self
        for name, l in self._sub_layers.items():
            if l is None or id(l) in layers_set:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from l.named_sublayers(prefix=sub_prefix, include_self=True,
                                         layers_set=layers_set)

    def apply(self, fn):
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    # ---- mode ----
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    # ---- hooks ----
    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # ---- call ----
    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, l in self._sub_layers.items():
            mod_str = repr(l)
            mod_str = "\n".join("  " + line for line in mod_str.split("\n"))
            lines.append(f"({name}): {mod_str.strip()}")
        main = self.__class__.__name__
        if not lines:
            return f"{main}({extra})"
        body = "\n".join("  " + l for l in lines)
        return f"{main}(\n{body}\n)"

    # ---- state ----
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(include_sublayers=include_sublayers):
            dest[structured_name_prefix + name] = p
        for name, b in self.named_buffers(include_sublayers=include_sublayers):
            layer, _, leaf = name.rpartition(".")
            owner = self
            if layer:
                for part in layer.split("."):
                    owner = owner._sub_layers[part]
            if leaf in owner._non_persistable_buffer_names:
                continue
            dest[structured_name_prefix + name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        missing, unexpected = [], []
        own = self.state_dict()
        matched = set()
        for key, value in state_dict.items():
            if key not in own:
                unexpected.append(key)
                continue
            target = own[key]
            arr = value._data if isinstance(value, Tensor) else jnp.asarray(np.asarray(value))
            if tuple(arr.shape) != tuple(target._data.shape):
                raise ValueError(
                    f"shape mismatch for '{key}': loaded {tuple(arr.shape)} vs "
                    f"expected {tuple(target._data.shape)}")
            # copy (the source may later be donated by a fused optimizer
            # step) AND re-place onto the target's own device/sharding (the
            # source may live on another pipeline stage's device).  An
            # uncommitted target (e.g. a PipelineLayer tied weight that
            # _place_stages leaves free to migrate between stage devices)
            # must stay uncommitted, so don't pin it to its current device.
            if getattr(target._data, "committed", True):
                target._data = jax.device_put(
                    jnp.array(arr, dtype=target._data.dtype, copy=True),
                    target._data.sharding)
            else:
                # host round-trip: the copy must not inherit the SOURCE's
                # committed device either (e.g. loading a pipeline-staged
                # state_dict into a fresh single-stage model)
                target._data = jnp.asarray(
                    np.asarray(arr), dtype=target._data.dtype)
            matched.add(key)
        missing = [k for k in own if k not in matched]
        return missing, unexpected

    load_dict = set_state_dict

    def to(self, device=None, dtype=None, blocking=None):
        import jax

        from ...core import place as place_mod
        dev = None
        if device is not None:
            p = device if isinstance(device, place_mod.Place) else place_mod._parse_device(device)
            dev = p.jax_device()
        dt = convert_dtype(dtype) if dtype is not None else None
        for t in list(self.parameters()) + list(self.buffers()):
            arr = t._data
            if dt is not None and jnp.issubdtype(arr.dtype, jnp.floating):
                arr = arr.astype(dt.np_dtype)
            if dev is not None:
                arr = jax.device_put(arr, dev)
            t._data = arr
        if dt is not None:
            self._dtype = dt
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def half(self):
        return self.to(dtype="float16")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def full_name(self):
        return self._name_scope
