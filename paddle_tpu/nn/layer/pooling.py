"""Pooling layers.  Parity with /root/reference/python/paddle/nn/layer/pooling.py."""
from __future__ import annotations

from .. import functional as F
from .layers import Layer

__all__ = ["AvgPool1D", "AvgPool2D", "AvgPool3D", "MaxPool1D", "MaxPool2D",
           "MaxPool3D", "AdaptiveAvgPool1D", "AdaptiveAvgPool2D",
           "AdaptiveAvgPool3D", "AdaptiveMaxPool1D", "AdaptiveMaxPool2D",
           "AdaptiveMaxPool3D", "LPPool1D", "LPPool2D"]


class _Pool(Layer):
    _fn = None

    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, return_mask=False,
                 data_format=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.exclusive = exclusive
        self.return_mask = return_mask
        self.data_format = data_format

    def forward(self, x):
        fn = type(self)._fn.__func__ if isinstance(type(self)._fn, staticmethod) else type(self)._fn
        kwargs = dict(stride=self.stride, padding=self.padding,
                      ceil_mode=self.ceil_mode, data_format=self.data_format)
        if "max" in fn.__name__:
            kwargs["return_mask"] = self.return_mask
        else:
            kwargs["exclusive"] = self.exclusive
        return fn(x, self.kernel_size, **kwargs)


class MaxPool1D(_Pool):
    _fn = staticmethod(F.max_pool1d)


class MaxPool2D(_Pool):
    _fn = staticmethod(F.max_pool2d)


class MaxPool3D(_Pool):
    _fn = staticmethod(F.max_pool3d)


class AvgPool1D(_Pool):
    _fn = staticmethod(F.avg_pool1d)


class AvgPool2D(_Pool):
    _fn = staticmethod(F.avg_pool2d)


class AvgPool3D(_Pool):
    _fn = staticmethod(F.avg_pool3d)


class _AdaptivePool(Layer):
    _fn = None

    def __init__(self, output_size, return_mask=False, data_format=None, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask
        self.data_format = data_format

    def forward(self, x):
        fn = type(self)._fn.__func__ if isinstance(type(self)._fn, staticmethod) else type(self)._fn
        if "max" in fn.__name__:
            return fn(x, self.output_size, return_mask=self.return_mask,
                      data_format=self.data_format)
        return fn(x, self.output_size, data_format=self.data_format)


class AdaptiveAvgPool1D(_AdaptivePool):
    _fn = staticmethod(F.adaptive_avg_pool1d)


class AdaptiveAvgPool2D(_AdaptivePool):
    _fn = staticmethod(F.adaptive_avg_pool2d)


class AdaptiveAvgPool3D(_AdaptivePool):
    _fn = staticmethod(F.adaptive_avg_pool3d)


class AdaptiveMaxPool1D(_AdaptivePool):
    _fn = staticmethod(F.adaptive_max_pool1d)


class AdaptiveMaxPool2D(_AdaptivePool):
    _fn = staticmethod(F.adaptive_max_pool2d)


class AdaptiveMaxPool3D(_AdaptivePool):
    _fn = staticmethod(F.adaptive_max_pool3d)


class LPPool1D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCL", name=None):
        super().__init__()
        self.args = (norm_type, kernel_size, stride, padding, ceil_mode, data_format)

    def forward(self, x):
        n, k, s, p, c, df = self.args
        return F.lp_pool1d(x, n, k, s, p, c, df)


class LPPool2D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__()
        self.args = (norm_type, kernel_size, stride, padding, ceil_mode, data_format)

    def forward(self, x):
        n, k, s, p, c, df = self.args
        return F.lp_pool2d(x, n, k, s, p, c, df)
