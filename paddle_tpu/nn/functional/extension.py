"""Remaining functional surface (reference python/paddle/nn/functional/
{activation,common,pooling,loss,extension,flash_attention}.py entries not
covered elsewhere): inplace activation twins, distance, feature-alpha
dropout, 1-D/3-D unpooling, fractional pooling, margin softmax,
class-center sampling, adaptive log softmax, qkv-packed flash attention.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core import dispatch as D
from ...core.tensor import Tensor

__all__ = [
    "pairwise_distance", "elu_", "hardtanh_", "leaky_relu_",
    "thresholded_relu_", "relu_", "sequence_mask", "gather_tree",
    "temporal_shift", "feature_alpha_dropout", "max_unpool1d",
    "max_unpool3d", "fractional_max_pool2d", "fractional_max_pool3d",
    "margin_cross_entropy", "class_center_sample",
    "adaptive_log_softmax_with_loss", "sparse_attention",
    "flashmask_attention", "flash_attn_qkvpacked",
    "flash_attn_varlen_qkvpacked", "rnnt_loss",
]


def _t(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    """(reference nn/functional/distance.py pairwise_distance)."""
    def impl(a, b, p, eps, keepdim):
        d = (a - b).astype(jnp.float32) + eps
        out = jnp.sum(jnp.abs(d) ** p, axis=-1, keepdims=keepdim) ** (1.0 / p)
        return out.astype(a.dtype)

    return D.apply("pairwise_distance", impl, (x, y),
                   {"p": float(p), "eps": float(epsilon),
                    "keepdim": bool(keepdim)})


def _inplace(fn):
    def wrapped(x, *args, **kwargs):
        out = fn(x, *args, **kwargs)
        x._data = out._data
        return x
    return wrapped


def elu_(x, alpha=1.0, name=None):
    from .activation import elu
    return _inplace(elu)(x, alpha)


def relu_(x, name=None):
    from .activation import relu
    return _inplace(relu)(x)


def hardtanh_(x, min=-1.0, max=1.0, name=None):
    from .activation import hardtanh
    return _inplace(hardtanh)(x, min, max)


def leaky_relu_(x, negative_slope=0.01, name=None):
    from .activation import leaky_relu
    return _inplace(leaky_relu)(x, negative_slope)


def thresholded_relu_(x, threshold=1.0, value=0.0, name=None):
    from .activation import thresholded_relu
    return _inplace(thresholded_relu)(x, threshold, value)


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    from ...ops.misc import sequence_mask as _impl
    return _impl(x, maxlen, dtype)


def gather_tree(ids, parents, name=None):
    from ...ops.misc import gather_tree as _impl
    return _impl(ids, parents)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    from ...ops.misc import temporal_shift as _impl
    return _impl(x, seg_num, shift_ratio, data_format)


def feature_alpha_dropout(x, p=0.5, training=True, name=None):
    """Alpha dropout over whole channels (reference common.py
    feature_alpha_dropout: SELU-preserving statistics, channel granularity
    on [N, C, ...])."""
    if not training or p == 0.0:
        return x if isinstance(x, Tensor) else Tensor(_t(x))
    import random as _r
    seed = _r.randint(0, 2 ** 31 - 1)

    def impl(a, p, seed):
        alpha_p = -1.7580993408473766
        keep = 1.0 - p
        key = jax.random.PRNGKey(seed)
        mask_shape = a.shape[:2] + (1,) * (a.ndim - 2)
        mask = jax.random.bernoulli(key, keep, mask_shape)
        af = a.astype(jnp.float32)
        a_scale = (keep + alpha_p ** 2 * keep * p) ** -0.5
        b = -a_scale * p * alpha_p
        out = jnp.where(mask, af, alpha_p)
        return (out * a_scale + b).astype(a.dtype)

    return D.apply("feature_alpha_dropout", impl, (x,),
                   {"p": float(p), "seed": seed})


def _unpool_nd(x, indices, spatial):
    def impl(a, idx, out_sizes):
        lead = a.shape[:2]
        flat_out = 1
        for s in out_sizes:
            flat_out *= s
        av = a.reshape(lead + (-1,))
        iv = idx.reshape(lead + (-1,)).astype(jnp.int32)
        base = jnp.zeros(lead + (flat_out,), a.dtype)
        out = jax.vmap(jax.vmap(
            lambda dst, src, ii: dst.at[ii].set(src)))(base, av, iv)
        return out.reshape(lead + tuple(out_sizes))
    return impl


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    """(reference pooling.py max_unpool1d)."""
    if data_format != "NCL":
        raise ValueError("max_unpool1d supports NCL only")
    ks = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
    st = ks if stride is None else (
        stride if isinstance(stride, int) else stride[0])
    pd = padding if isinstance(padding, int) else padding[0]
    L = x.shape[-1]
    Lo = (int(output_size[-1]) if output_size is not None
          else (L - 1) * st - 2 * pd + ks)
    return D.apply("max_unpool1d", _unpool_nd(x, indices, 1),
                   (x, indices), {"out_sizes": (Lo,)})


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    """(reference pooling.py max_unpool3d)."""
    if data_format != "NCDHW":
        raise ValueError("max_unpool3d supports NCDHW only")
    ks = (kernel_size,) * 3 if isinstance(kernel_size, int) \
        else tuple(kernel_size)
    st = ks if stride is None else (
        (stride,) * 3 if isinstance(stride, int) else tuple(stride))
    pd = (padding,) * 3 if isinstance(padding, int) else tuple(padding)
    D_, H, W = x.shape[-3:]
    if output_size is not None:
        out_sizes = tuple(int(v) for v in output_size[-3:])
    else:
        out_sizes = tuple((n - 1) * s - 2 * p + k for n, s, p, k in
                          zip((D_, H, W), st, pd, ks))
    return D.apply("max_unpool3d", _unpool_nd(x, indices, 3),
                   (x, indices), {"out_sizes": out_sizes})


def _fractional_pool(x, output_size, random_u, nd, kernel_size=None):
    """Fractional max pooling: pseudo-random pooling regions from the u
    sequence (Graham 2014; reference pooling.py fractional_max_pool2d)."""
    import random as _r
    u = float(random_u) if random_u is not None else _r.random()
    u = min(max(u, 1e-4), 1.0 - 1e-4)

    def bounds(n_in, n_out):
        # region edges partition [0, n_in): interior edges from the u
        # sequence, endpoints pinned (Graham 2014 pseudo-random regions)
        alpha = n_in / n_out
        inner = jnp.floor(alpha * (jnp.arange(1, n_out) + u)).astype(
            jnp.int32)
        inner = jnp.clip(inner, 1, n_in - 1)
        edges = jnp.concatenate([jnp.zeros((1,), jnp.int32), inner,
                                 jnp.full((1,), n_in, jnp.int32)])
        starts = edges[:-1]
        ends = jnp.maximum(edges[1:], starts + 1)
        return starts, jnp.minimum(ends, n_in)

    def impl(a, out_sizes):
        spatial = a.shape[-nd:]
        out = a
        # pool one spatial dim at a time (max is separable)
        for d in range(nd):
            n_in = spatial[d]
            n_out = out_sizes[d]
            starts, ends = bounds(n_in, n_out)
            axis = a.ndim - nd + d

            def pool_dim(i):
                s = starts[i]
                e = ends[i]
                # static max window: alpha+1 elements, mask the overhang
                w = int(-(-n_in // n_out)) + 1
                sl = jax.lax.dynamic_slice_in_dim(
                    out, jnp.minimum(s, n_in - w), w, axis=axis)
                pos = jnp.minimum(s, n_in - w) + jnp.arange(w)
                valid = (pos >= s) & (pos < e)
                shape = [1] * sl.ndim
                shape[axis] = w
                sl = jnp.where(valid.reshape(shape), sl, -jnp.inf)
                return jnp.max(sl, axis=axis)

            out = jnp.stack([pool_dim(i) for i in range(n_out)], axis=axis)
        return out.astype(a.dtype)

    return impl


def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    out = D.apply("fractional_max_pool2d",
                  _fractional_pool(x, output_size, random_u, 2),
                  (x,), {"out_sizes": tuple(int(v) for v in output_size)})
    if return_mask:
        raise NotImplementedError(
            "fractional_max_pool2d(return_mask=True) is not implemented")
    return out


def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    if isinstance(output_size, int):
        output_size = (output_size,) * 3
    out = D.apply("fractional_max_pool3d",
                  _fractional_pool(x, output_size, random_u, 3),
                  (x,), {"out_sizes": tuple(int(v) for v in output_size)})
    if return_mask:
        raise NotImplementedError(
            "fractional_max_pool3d(return_mask=True) is not implemented")
    return out


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean", name=None):
    """ArcFace-family margin softmax (reference loss.py
    margin_cross_entropy: cos(m1*theta + m2) - m3 on the target logit,
    then scaled softmax CE).  Single-group (non-model-parallel) form."""
    def impl(lg, lb, m1, m2, m3, s, reduction, want_softmax):
        lgf = jnp.clip(lg.astype(jnp.float32), -1.0, 1.0)
        theta = jnp.arccos(jnp.take_along_axis(lgf, lb[:, None], 1)[:, 0])
        target = jnp.cos(m1 * theta + m2) - m3
        adj = lgf.at[jnp.arange(lgf.shape[0]), lb].set(target) * s
        lse = jax.scipy.special.logsumexp(adj, axis=-1)
        picked = jnp.take_along_axis(adj, lb[:, None], 1)[:, 0]
        loss = lse - picked
        if reduction == "mean":
            loss = jnp.mean(loss)
        elif reduction == "sum":
            loss = jnp.sum(loss)
        if want_softmax:
            return loss, jax.nn.softmax(adj, axis=-1)
        return loss

    lb = label.flatten() if hasattr(label, "flatten") else label
    kwargs = {"m1": float(margin1), "m2": float(margin2),
              "m3": float(margin3), "s": float(scale),
              "reduction": str(reduction),
              "want_softmax": bool(return_softmax)}
    if return_softmax:
        return D.apply("margin_cross_entropy", impl, (logits, lb), kwargs,
                       num_outputs=2)
    return D.apply("margin_cross_entropy", impl, (logits, lb), kwargs)


def class_center_sample(label, num_classes, num_samples, group=None,
                        name=None):
    """Sample negative class centers + remap labels (reference
    common.py class_center_sample).  Host-side sampling (data-dependent
    unique), like the reference's CPU path."""
    import numpy as np

    lb = np.asarray(label.numpy() if isinstance(label, Tensor) else label)
    pos = np.unique(lb)
    remaining = np.setdiff1d(np.arange(num_classes), pos)
    n_extra = max(0, int(num_samples) - pos.size)
    rng = np.random.default_rng()
    extra = (rng.choice(remaining, size=min(n_extra, remaining.size),
                        replace=False) if n_extra else
             np.empty((0,), lb.dtype))
    sampled = np.sort(np.concatenate([pos, extra])).astype(lb.dtype)
    remap = {int(c): i for i, c in enumerate(sampled)}
    new_label = np.asarray([remap[int(v)] for v in lb.reshape(-1)],
                           lb.dtype).reshape(lb.shape)
    return (Tensor(jnp.asarray(new_label)), Tensor(jnp.asarray(sampled)))


def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None, name=None):
    """Adaptive softmax (reference loss.py adaptive_log_softmax_with_loss;
    Grave et al.): frequent classes in the head, rare classes through
    per-cluster tail projections."""
    def impl(x, lb, hw, *rest, cutoffs, has_bias):
        xf = x.astype(jnp.float32)
        n_clusters = len(cutoffs) - 1
        head_size = cutoffs[0] + n_clusters
        if has_bias:
            hb, tails = rest[0], rest[1:]
        else:
            hb, tails = None, rest
        head = xf @ hw.astype(jnp.float32)
        if hb is not None:
            head = head + hb.astype(jnp.float32)
        head_lsm = jax.nn.log_softmax(head, axis=-1)
        out = jnp.zeros((x.shape[0],), jnp.float32)
        # in-head targets
        in_head = lb < cutoffs[0]
        safe = jnp.clip(lb, 0, head_size - 1)
        out = jnp.where(in_head,
                        jnp.take_along_axis(head_lsm, safe[:, None],
                                            1)[:, 0], out)
        for i in range(n_clusters):
            lo, hi = cutoffs[i], cutoffs[i + 1]
            w1, w2 = tails[2 * i], tails[2 * i + 1]
            proj = (xf @ w1.astype(jnp.float32)) @ w2.astype(jnp.float32)
            tail_lsm = jax.nn.log_softmax(proj, axis=-1)
            in_tail = (lb >= lo) & (lb < hi)
            rel = jnp.clip(lb - lo, 0, hi - lo - 1)
            cluster_lp = head_lsm[:, cutoffs[0] + i]
            lp = cluster_lp + jnp.take_along_axis(tail_lsm, rel[:, None],
                                                  1)[:, 0]
            out = jnp.where(in_tail, lp, out)
        return out, -jnp.mean(out)

    flat_tails = []
    for pair in tail_weights:
        flat_tails.extend(pair)
    has_bias = head_bias is not None
    args = (input, label, head_weight) + \
        ((head_bias,) if has_bias else ()) + tuple(flat_tails)
    return D.apply("adaptive_log_softmax_with_loss", impl, args,
                   {"cutoffs": tuple(int(c) for c in cutoffs),
                    "has_bias": has_bias},
                   num_outputs=2)


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    raise NotImplementedError(
        "sparse_attention (block-sparse SDPA) is not implemented in this "
        "TPU build — the reference gates it behind a CUDA-only kernel "
        "(sparse_attention_kernel.cu). Use flash_attn_unpadded or a dense "
        "mask with scaled_dot_product_attention; a Pallas block-sparse "
        "kernel can be registered via paddle_tpu.utils.cpp_extension")


def flashmask_attention(query, key, value, startend_row_indices=None,
                        dropout=0.0, causal=False, window_size=None,
                        return_softmax_lse=False, return_seed_offset=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """FlashMask (reference flash_attention.py flashmask_attention):
    row-bounded sparse masks.  Here the row bounds materialize into a dense
    additive mask consumed by the fused SDPA path (numerics-equal; the
    O(S) mask representation is an optimization the Pallas kernel can adopt
    later)."""
    from .attention import scaled_dot_product_attention

    if startend_row_indices is None:
        return scaled_dot_product_attention(query, key, value,
                                            is_causal=causal)
    idx = _t(startend_row_indices)       # [B, KVH, S, {1,2,4}]
    S = query.shape[1]
    rows = jnp.arange(S)[:, None]        # mask rows (query positions)
    if idx.shape[-1] == 1:
        if not causal:
            raise ValueError(
                "flashmask_attention: the 1-column (LT-start) layout is "
                "causal-only in the reference; pass causal=True or use the "
                "2/4-column layouts")
        # masked when row >= start[col]
        masked = rows[None, None] >= idx[..., 0][:, :, None, :]
    elif idx.shape[-1] == 2:
        start, end = idx[..., 0], idx[..., 1]
        masked = (rows[None, None] >= start[:, :, None, :]) & \
                 (rows[None, None] < end[:, :, None, :])
    else:
        ls, le, us, ue = (idx[..., i] for i in range(4))
        masked = ((rows[None, None] >= ls[:, :, None, :]) &
                  (rows[None, None] < le[:, :, None, :])) | \
                 ((rows[None, None] >= us[:, :, None, :]) &
                  (rows[None, None] < ue[:, :, None, :]))
    # masked: [B, KVH, S(q), S(k)] -> additive bias broadcast over heads
    nheads = query.shape[2]
    kvh = masked.shape[1]
    if kvh != nheads:
        masked = jnp.repeat(masked, nheads // kvh, axis=1)
    bias = jnp.where(masked, -jnp.inf, 0.0)
    if causal:
        causal_m = rows < jnp.arange(S)[None, :]
        bias = bias + jnp.where(causal_m[None, None], -jnp.inf, 0.0)
    return scaled_dot_product_attention(query, key, value,
                                        attn_mask=Tensor(bias))


def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False,
                         return_softmax=False, fixed_seed_offset=None,
                         rng_name="", training=True, name=None):
    """Packed [B, S, 3, H, D] qkv (reference flash_attention.py
    flash_attn_qkvpacked) — unpacks and rides the fused attention path."""
    from .attention import flash_attention

    q = qkv[:, :, 0]
    k = qkv[:, :, 1]
    v = qkv[:, :, 2]
    return flash_attention(q, k, v, dropout=dropout, causal=causal,
                           return_softmax=return_softmax, training=training)


def flash_attn_varlen_qkvpacked(qkv, cu_seqlens_q, cu_seqlens_k,
                                max_seqlen_q, max_seqlen_k, scale,
                                dropout=0.0, causal=False,
                                return_softmax=False, fixed_seed_offset=None,
                                rng_name="", varlen_padded=True,
                                training=True, name=None):
    """Packed varlen [T, 3, H, D] (reference flash_attn_varlen_qkvpacked)."""
    from .attention import flash_attn_unpadded

    q = qkv[:, 0]
    k = qkv[:, 1]
    v = qkv[:, 2]
    return flash_attn_unpadded(q, k, v, cu_seqlens_q, cu_seqlens_k,
                               max_seqlen_q, max_seqlen_k, scale,
                               dropout=dropout, causal=causal,
                               return_softmax=return_softmax,
                               training=training)


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    """RNN-T transducer loss (reference loss.py rnnt_loss; kernel
    warprnnt).  Forward-variable DP over the (T, U) lattice via lax.scan
    (log-space), batched; differentiable through the scan.
    input: [B, T, U+1, V] log-probable activations (softmaxed internally),
    label: [B, U]."""
    def impl(acts, labels, in_lens, lb_lens, blank, reduction):
        lp = jax.nn.log_softmax(acts.astype(jnp.float32), axis=-1)
        B, T, U1, V = lp.shape
        U = U1 - 1
        NEG = -1e30

        blank_lp = lp[..., blank]                       # [B, T, U+1]
        lab_lp = jnp.take_along_axis(
            lp[:, :, :U, :], labels[:, None, :, None].astype(jnp.int32),
            axis=-1)[..., 0]                            # [B, T, U]

        # alpha over t, carried row alpha[:, u] for u in 0..U
        alpha0 = jnp.concatenate(
            [jnp.zeros((B, 1)), jnp.full((B, U), NEG)], axis=1)

        def emit_row(alpha_row, lab_t):
            # within one t: alpha[u] += emit from alpha[u-1]
            def body(u, row):
                cand = row[:, u - 1] + lab_t[:, u - 1]
                return row.at[:, u].set(jnp.logaddexp(row[:, u], cand))
            return jax.lax.fori_loop(1, U + 1, body, alpha_row)

        def step(alpha, t):
            # blank at (t-1, u) advances time; emissions run within frame t
            moved = alpha + blank_lp[:, t - 1]
            emitted = emit_row(moved, lab_lp[:, t])
            return emitted, alpha

        # t = 0: only emissions within the first frame
        alpha_t0 = emit_row(alpha0, lab_lp[:, 0])
        # scan emits the PRE-step carry (rows 0..T-2); the final carry is
        # row T-1
        alpha_last, prev_rows = jax.lax.scan(step, alpha_t0,
                                             jnp.arange(1, T))
        all_alpha = jnp.concatenate([prev_rows, alpha_last[None]], axis=0)
        # gather alpha at (T_b - 1, U_b) + final blank
        tb = jnp.clip(in_lens.astype(jnp.int32) - 1, 0, T - 1)
        ub = jnp.clip(lb_lens.astype(jnp.int32), 0, U)
        a_final = all_alpha[tb, jnp.arange(B), ub]
        final_blank = blank_lp[jnp.arange(B), tb, ub]
        nll = -(a_final + final_blank)
        if reduction == "mean":
            return jnp.mean(nll)
        if reduction == "sum":
            return jnp.sum(nll)
        return nll

    return D.apply("rnnt_loss", impl,
                   (input, label, input_lengths, label_lengths),
                   {"blank": int(blank), "reduction": str(reduction)})
