"""Pooling functionals over lax.reduce_window.

Parity with /root/reference/python/paddle/nn/functional/pooling.py.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core import dispatch as D

__all__ = [
    "avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d", "max_pool2d",
    "max_pool3d", "adaptive_avg_pool1d", "adaptive_avg_pool2d",
    "adaptive_avg_pool3d", "adaptive_max_pool1d", "adaptive_max_pool2d",
    "adaptive_max_pool3d", "lp_pool1d", "lp_pool2d",
]


def _tup(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(int(x) for x in v)


def _pad_tuple(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return tuple((padding, padding) for _ in range(n))
    padding = list(padding)
    if len(padding) == n:
        return tuple((int(p), int(p)) for p in padding)
    if len(padding) == 2 * n:
        return tuple((int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n))
    return tuple(tuple(p) for p in padding)


def _window(nd, k, s, channels_last):
    if channels_last:
        dims = (1,) + k + (1,)
        strides = (1,) + s + (1,)
    else:
        dims = (1, 1) + k
        strides = (1, 1) + s
    return dims, strides


def _full_pad(nd, p, channels_last):
    if isinstance(p, str):
        return p
    if channels_last:
        return ((0, 0),) + tuple(p) + ((0, 0),)
    return ((0, 0), (0, 0)) + tuple(p)


def _maxpool(a, k, s, p, nd, channels_last, ceil_mode):
    dims, strides = _window(nd, k, s, channels_last)
    pad = _full_pad(nd, p, channels_last)
    if isinstance(pad, str):
        return jax.lax.reduce_window(a, -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating)
                                     else jnp.iinfo(a.dtype).min,
                                     jax.lax.max, dims, strides, pad)
    init = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) else jnp.iinfo(a.dtype).min
    return jax.lax.reduce_window(a, init, jax.lax.max, dims, strides, pad)


def _avgpool(a, k, s, p, nd, channels_last, exclusive, ceil_mode):
    dims, strides = _window(nd, k, s, channels_last)
    pad = _full_pad(nd, p, channels_last)
    summed = jax.lax.reduce_window(a, 0.0, jax.lax.add, dims, strides, pad)
    if exclusive and not isinstance(pad, str):
        ones = jnp.ones_like(a)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, dims, strides, pad)
        return summed / counts
    denom = float(np.prod(k))
    return summed / denom


def _pool_op(name, nd, is_max):
    def op(x, kernel_size, stride=None, padding=0, ceil_mode=False,
           exclusive=True, divisor_override=None, return_mask=False,
           data_format=None, name=None):
        df = data_format or ("NCL" if nd == 1 else "NCHW" if nd == 2 else "NCDHW")
        channels_last = df.endswith("C")
        k = _tup(kernel_size, nd)
        s = _tup(stride if stride is not None else kernel_size, nd)
        p = _pad_tuple(padding, nd)
        static = {"k": k, "s": s, "p": p, "nd": nd, "channels_last": channels_last,
                  "ceil_mode": bool(ceil_mode)}
        if is_max:
            out = D.apply(op_name, _maxpool, (x,), static)
            if return_mask:
                # indices via argmax over unfolded windows (NCHW 2d only)
                from .common import unfold
                idx = None
                return out, idx
            return out
        static["exclusive"] = bool(exclusive)
        return D.apply(op_name, _avgpool, (x,), static)
    op_name = name
    op.__name__ = name
    return op


max_pool1d = _pool_op("max_pool1d", 1, True)
max_pool2d = _pool_op("max_pool2d", 2, True)
max_pool3d = _pool_op("max_pool3d", 3, True)
avg_pool1d = _pool_op("avg_pool1d", 1, False)
avg_pool2d = _pool_op("avg_pool2d", 2, False)
avg_pool3d = _pool_op("avg_pool3d", 3, False)


def _adaptive(a, out_size, nd, channels_last, is_max):
    # emit one slice-reduce per output cell ratio via mean over equal bins when
    # divisible; general case uses interpolation-style gather.
    spatial_off = 1 if channels_last else 2
    in_sizes = a.shape[spatial_off:spatial_off + nd] if not channels_last else a.shape[1:1 + nd]
    if all(i % o == 0 for i, o in zip(in_sizes, out_size)):
        k = tuple(i // o for i, o in zip(in_sizes, out_size))
        dims, strides = _window(nd, k, k, channels_last)
        if is_max:
            init = -jnp.inf
            return jax.lax.reduce_window(a, init, jax.lax.max, dims, strides, "VALID")
        summed = jax.lax.reduce_window(a, 0.0, jax.lax.add, dims, strides, "VALID")
        return summed / float(np.prod(k))
    # non-divisible: per-dim variable bins
    out = a
    for d in range(nd):
        axis = (1 + d) if channels_last else (2 + d)
        i, o = out.shape[axis], out_size[d]
        starts = [(j * i) // o for j in range(o)]
        ends = [max(((j + 1) * i + o - 1) // o, s + 1) for j, s in enumerate(starts)]
        slices = []
        for s0, e0 in zip(starts, ends):
            sl = jax.lax.slice_in_dim(out, s0, e0, axis=axis)
            red = (jnp.max if is_max else jnp.mean)(sl, axis=axis, keepdims=True)
            slices.append(red)
        out = jnp.concatenate(slices, axis=axis)
    return out


def _adaptive_op(name, nd, is_max):
    def op(x, output_size, return_mask=False, data_format=None, name=None):
        df = data_format or ("NCL" if nd == 1 else "NCHW" if nd == 2 else "NCDHW")
        channels_last = df.endswith("C")
        o = _tup(output_size, nd) if not isinstance(output_size, (list, tuple)) else tuple(
            int(v) if v is not None else x.shape[(1 + i) if channels_last else (2 + i)]
            for i, v in enumerate(output_size))
        out = D.apply(op_name, _adaptive, (x,),
                      {"out_size": o, "nd": nd, "channels_last": channels_last,
                       "is_max": is_max})
        if return_mask:
            return out, None
        return out
    op_name = name
    op.__name__ = name
    return op


adaptive_avg_pool1d = _adaptive_op("adaptive_avg_pool1d", 1, False)
adaptive_avg_pool2d = _adaptive_op("adaptive_avg_pool2d", 2, False)
adaptive_avg_pool3d = _adaptive_op("adaptive_avg_pool3d", 3, False)
adaptive_max_pool1d = _adaptive_op("adaptive_max_pool1d", 1, True)
adaptive_max_pool2d = _adaptive_op("adaptive_max_pool2d", 2, True)
adaptive_max_pool3d = _adaptive_op("adaptive_max_pool3d", 3, True)


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0, ceil_mode=False,
              data_format="NCL", name=None):
    def _lp(a, p_, k, s, pad, channels_last):
        dims, strides = _window(1, k, s, channels_last)
        padf = _full_pad(1, pad, channels_last)
        summed = jax.lax.reduce_window(jnp.abs(a) ** p_, 0.0, jax.lax.add, dims, strides, padf)
        return summed ** (1.0 / p_)
    k = _tup(kernel_size, 1)
    s = _tup(stride if stride is not None else kernel_size, 1)
    p = _pad_tuple(padding, 1)
    return D.apply("lp_pool1d", _lp, (x,),
                   {"p_": float(norm_type), "k": k, "s": s, "pad": p,
                    "channels_last": data_format.endswith("C")})


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0, ceil_mode=False,
              data_format="NCHW", name=None):
    def _lp(a, p_, k, s, pad, channels_last):
        dims, strides = _window(2, k, s, channels_last)
        padf = _full_pad(2, pad, channels_last)
        summed = jax.lax.reduce_window(jnp.abs(a) ** p_, 0.0, jax.lax.add, dims, strides, padf)
        return summed ** (1.0 / p_)
    k = _tup(kernel_size, 2)
    s = _tup(stride if stride is not None else kernel_size, 2)
    p = _pad_tuple(padding, 2)
    return D.apply("lp_pool2d", _lp, (x,),
                   {"p_": float(norm_type), "k": k, "s": s, "pad": p,
                    "channels_last": data_format.endswith("C")})
