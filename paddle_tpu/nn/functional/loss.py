"""Loss functionals.

Parity with /root/reference/python/paddle/nn/functional/loss.py.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core import dispatch as D
from ...core.tensor import Tensor

__all__ = [
    "cross_entropy", "softmax_with_cross_entropy", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "nll_loss", "l1_loss", "mse_loss",
    "smooth_l1_loss", "kl_div", "margin_ranking_loss", "hinge_embedding_loss",
    "cosine_embedding_loss", "triplet_margin_loss",
    "triplet_margin_with_distance_loss", "multi_label_soft_margin_loss",
    "soft_margin_loss", "sigmoid_focal_loss", "log_loss", "square_error_cost",
    "ctc_loss", "poisson_nll_loss", "gaussian_nll_loss", "hsigmoid_loss",
    "npair_loss", "dice_loss", "multi_margin_loss",
]


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                  name=None):
    def _ce(logits, lbl, *w, ignore_index, reduction, soft_label, axis, use_softmax,
            label_smoothing, has_w):
        if use_softmax:
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis)
        else:
            logp = jnp.log(jnp.clip(logits.astype(jnp.float32), 1e-12, None))
        n_class = logits.shape[axis]
        if soft_label or (lbl.ndim == logits.ndim and lbl.shape[axis] == n_class
                          and jnp.issubdtype(lbl.dtype, jnp.floating)):
            soft = lbl.astype(logp.dtype)
            if label_smoothing > 0:
                soft = soft * (1 - label_smoothing) + label_smoothing / n_class
            loss = -jnp.sum(soft * logp, axis=axis)
            if has_w:
                wvec = w[0].astype(logp.dtype)
                shape = [1] * logp.ndim
                shape[axis] = n_class
                loss = loss * jnp.sum(soft * wvec.reshape(shape), axis=axis)
            return _reduce(loss, reduction)
        lbl_i = lbl
        if lbl_i.ndim == logits.ndim:
            lbl_i = jnp.squeeze(lbl_i, axis=axis)
        lbl_i = lbl_i.astype(jnp.int32)
        valid = lbl_i != ignore_index
        safe = jnp.where(valid, lbl_i, 0)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(safe, axis), axis=axis)
        loss = -jnp.squeeze(picked, axis)
        if label_smoothing > 0:
            smooth_loss = -jnp.mean(logp, axis=axis)
            loss = (1 - label_smoothing) * loss + label_smoothing * smooth_loss
        if has_w:
            wvec = w[0].astype(logp.dtype)
            sample_w = wvec[safe]
            loss = loss * sample_w
            loss = jnp.where(valid, loss, 0.0)
            if reduction == "mean":
                denom = jnp.sum(jnp.where(valid, sample_w, 0.0))
                return jnp.sum(loss) / jnp.maximum(denom, 1e-12)
            return _reduce(loss, reduction)
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            denom = jnp.maximum(jnp.sum(valid.astype(logp.dtype)), 1.0)
            return jnp.sum(loss) / denom
        return _reduce(loss, reduction)

    args = [input, label]
    if weight is not None:
        args.append(weight)
    return D.apply("cross_entropy", _ce, tuple(args),
                   {"ignore_index": int(ignore_index), "reduction": reduction,
                    "soft_label": bool(soft_label), "axis": int(axis),
                    "use_softmax": bool(use_softmax),
                    "label_smoothing": float(label_smoothing),
                    "has_w": weight is not None})


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False,
                               axis=-1, name=None):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    from .activation import softmax as _softmax
    from ...ops.manipulation import unsqueeze
    loss = unsqueeze(loss, axis)
    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def _bce(p, l, *w, reduction, has_w):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-7)
        loss = -(l * jnp.log(p) + (1 - l) * jnp.log(1 - p))
        if has_w:
            loss = loss * w[0]
        return _reduce(loss, reduction)
    args = [input, label] + ([weight] if weight is not None else [])
    return D.apply("binary_cross_entropy", _bce, tuple(args),
                   {"reduction": reduction, "has_w": weight is not None})


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    def _bcel(z, l, *extra, reduction, has_w, has_pw):
        i = 0
        w = pw = None
        if has_w:
            w = extra[i]; i += 1
        if has_pw:
            pw = extra[i]
        max_val = jnp.clip(-z, 0, None)
        if pw is not None:
            log_w = (pw - 1.0) * l + 1.0
            loss = (1.0 - l) * z + log_w * (jnp.log1p(jnp.exp(-jnp.abs(z))) + max_val)
        else:
            loss = jnp.clip(z, 0, None) - z * l + jnp.log1p(jnp.exp(-jnp.abs(z)))
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)
    args = [logit, label]
    if weight is not None:
        args.append(weight)
    if pos_weight is not None:
        args.append(pos_weight)
    return D.apply("bce_with_logits", _bcel, tuple(args),
                   {"reduction": reduction, "has_w": weight is not None,
                    "has_pw": pos_weight is not None})


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    def _nll(logp, l, *w, ignore_index, reduction, has_w):
        l = l.astype(jnp.int32)
        valid = l != ignore_index
        safe = jnp.where(valid, l, 0)
        if logp.ndim > 2:
            # [N, C, d1...] -> move C last
            lp = jnp.moveaxis(logp, 1, -1)
        else:
            lp = logp
        picked = jnp.take_along_axis(lp, safe[..., None], axis=-1)[..., 0]
        loss = -picked
        if has_w:
            sw = w[0][safe]
            loss = loss * sw
            loss = jnp.where(valid, loss, 0.0)
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(jnp.where(valid, sw, 0.0)), 1e-12)
            return _reduce(loss, reduction)
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
        return _reduce(loss, reduction)
    args = [input, label] + ([weight] if weight is not None else [])
    return D.apply("nll_loss", _nll, tuple(args),
                   {"ignore_index": int(ignore_index), "reduction": reduction,
                    "has_w": weight is not None})


def l1_loss(input, label, reduction="mean", name=None):
    return D.apply("l1_loss",
                   lambda a, b, reduction: _reduce(jnp.abs(a - b), reduction),
                   (input, label), {"reduction": reduction})


def mse_loss(input, label, reduction="mean", name=None):
    return D.apply("mse_loss",
                   lambda a, b, reduction: _reduce(jnp.square(a - b), reduction),
                   (input, label), {"reduction": reduction})


def square_error_cost(input, label):
    return D.apply("square_error_cost", lambda a, b: jnp.square(a - b), (input, label))


def log_loss(input, label, epsilon=1e-4, name=None):
    return D.apply("log_loss",
                   lambda p, l, eps: -l * jnp.log(p + eps) - (1 - l) * jnp.log(1 - p + eps),
                   (input, label), {"eps": float(epsilon)})


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def _sl1(a, b, reduction, delta):
        d = a - b
        abs_d = jnp.abs(d)
        loss = jnp.where(abs_d < delta, 0.5 * d * d / delta, abs_d - 0.5 * delta)
        # paddle's smooth_l1_loss multiplies by delta
        loss = loss * delta
        return _reduce(loss, reduction)
    return D.apply("smooth_l1_loss", _sl1, (input, label),
                   {"reduction": reduction, "delta": float(delta)})


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def _kl(logp, t, reduction, log_target):
        if log_target:
            loss = jnp.exp(t) * (t - logp)
        else:
            loss = t * (jnp.log(jnp.clip(t, 1e-12, None)) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)
    return D.apply("kl_div", _kl, (input, label),
                   {"reduction": reduction, "log_target": bool(log_target)})


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    return D.apply("margin_ranking_loss",
                   lambda a, b, l, margin, reduction: _reduce(
                       jnp.clip(-l * (a - b) + margin, 0, None), reduction),
                   (input, other, label),
                   {"margin": float(margin), "reduction": reduction})


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    return D.apply("hinge_embedding_loss",
                   lambda a, l, margin, reduction: _reduce(
                       jnp.where(l == 1, a, jnp.clip(margin - a, 0, None)), reduction),
                   (input, label), {"margin": float(margin), "reduction": reduction})


def cosine_embedding_loss(input1, input2, label, margin=0, reduction="mean", name=None):
    def _cel(a, b, l, margin, reduction):
        cos = jnp.sum(a * b, -1) / (jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1) + 1e-12)
        loss = jnp.where(l == 1, 1 - cos, jnp.clip(cos - margin, 0, None))
        return _reduce(loss, reduction)
    return D.apply("cosine_embedding_loss", _cel, (input1, input2, label),
                   {"margin": float(margin), "reduction": reduction})


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6,
                        swap=False, reduction="mean", name=None):
    def _tml(a, pos, neg, margin, p, eps, swap, reduction):
        def dist(u, v):
            return jnp.sum(jnp.abs(u - v + eps) ** p, axis=-1) ** (1.0 / p)
        d_pos = dist(a, pos)
        d_neg = dist(a, neg)
        if swap:
            d_neg = jnp.minimum(d_neg, dist(pos, neg))
        return _reduce(jnp.clip(d_pos - d_neg + margin, 0, None), reduction)
    return D.apply("triplet_margin_loss", _tml, (input, positive, negative),
                   {"margin": float(margin), "p": float(p), "eps": float(epsilon),
                    "swap": bool(swap), "reduction": reduction})


def triplet_margin_with_distance_loss(input, positive, negative, distance_function=None,
                                      margin=1.0, swap=False, reduction="mean", name=None):
    if distance_function is None:
        return triplet_margin_loss(input, positive, negative, margin=margin,
                                   swap=swap, reduction=reduction)
    d_pos = distance_function(input, positive)
    d_neg = distance_function(input, negative)
    if swap:
        from ..functional import common  # noqa
        d_pn = distance_function(positive, negative)
        from ...ops.math import minimum
        d_neg = minimum(d_neg, d_pn)
    from ...ops.math import add, subtract
    from ...ops.math import clip
    diff = clip(add(subtract(d_pos, d_neg), margin), min=0.0)
    from ...ops.math import mean as _mean, sum as _sum
    if reduction == "mean":
        return _mean(diff)
    if reduction == "sum":
        return _sum(diff)
    return diff


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean", name=None):
    def _ml(z, l, *w, reduction, has_w):
        loss = -(l * jax.nn.log_sigmoid(z) + (1 - l) * jax.nn.log_sigmoid(-z))
        if has_w:
            loss = loss * w[0]
        loss = jnp.mean(loss, axis=-1)
        return _reduce(loss, reduction)
    args = [input, label] + ([weight] if weight is not None else [])
    return D.apply("multi_label_soft_margin_loss", _ml, tuple(args),
                   {"reduction": reduction, "has_w": weight is not None})


def soft_margin_loss(input, label, reduction="mean", name=None):
    return D.apply("soft_margin_loss",
                   lambda z, l, reduction: _reduce(jnp.log1p(jnp.exp(-l * z)), reduction),
                   (input, label), {"reduction": reduction})


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None, reduction="mean",
                      name=None):
    def _mm(z, l, *w, p, margin, reduction, has_w):
        n, c = z.shape
        correct = jnp.take_along_axis(z, l[:, None].astype(jnp.int32), axis=1)
        diff = jnp.clip(margin - correct + z, 0, None) ** p
        if has_w:
            diff = diff * w[0][l.astype(jnp.int32)][:, None]
        mask = 1.0 - jax.nn.one_hot(l.astype(jnp.int32), c, dtype=z.dtype)
        loss = jnp.sum(diff * mask, axis=1) / c
        return _reduce(loss, reduction)
    args = [input, label] + ([weight] if weight is not None else [])
    return D.apply("multi_margin_loss", _mm, tuple(args),
                   {"p": int(p), "margin": float(margin), "reduction": reduction,
                    "has_w": weight is not None})


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def _sfl(z, l, *n, alpha, gamma, reduction, has_n):
        p = jax.nn.sigmoid(z)
        ce = jnp.clip(z, 0, None) - z * l + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * l + (1 - p) * (1 - l)
        loss = ce * ((1 - p_t) ** gamma)
        if alpha >= 0:
            alpha_t = alpha * l + (1 - alpha) * (1 - l)
            loss = alpha_t * loss
        if has_n:
            loss = loss / n[0]
        return _reduce(loss, reduction)
    args = [logit, label] + ([normalizer] if normalizer is not None else [])
    return D.apply("sigmoid_focal_loss", _sfl, tuple(args),
                   {"alpha": float(alpha), "gamma": float(gamma),
                    "reduction": reduction, "has_n": normalizer is not None})


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    def _pnl(z, t, log_input, full, eps, reduction):
        if log_input:
            loss = jnp.exp(z) - t * z
        else:
            loss = z - t * jnp.log(z + eps)
        if full:
            stirling = t * jnp.log(t) - t + 0.5 * jnp.log(2 * np.pi * t)
            loss = loss + jnp.where(t > 1, stirling, 0.0)
        return _reduce(loss, reduction)
    return D.apply("poisson_nll_loss", _pnl, (input, label),
                   {"log_input": bool(log_input), "full": bool(full),
                    "eps": float(epsilon), "reduction": reduction})


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    def _gnl(mu, t, var, full, eps, reduction):
        var = jnp.clip(var, eps, None)
        loss = 0.5 * (jnp.log(var) + jnp.square(mu - t) / var)
        if full:
            loss = loss + 0.5 * np.log(2 * np.pi)
        return _reduce(loss, reduction)
    return D.apply("gaussian_nll_loss", _gnl, (input, label, variance),
                   {"full": bool(full), "eps": float(epsilon), "reduction": reduction})


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    def _np(a, p, l, l2_reg):
        batch = a.shape[0]
        sim = a @ p.T
        eq = (l[:, None] == l[None, :]).astype(a.dtype)
        eq = eq / jnp.sum(eq, axis=1, keepdims=True)
        logp = jax.nn.log_softmax(sim, axis=1)
        ce = -jnp.mean(jnp.sum(eq * logp, axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, 1)) + jnp.mean(jnp.sum(p * p, 1))) * 0.25
        return ce + reg
    return D.apply("npair_loss", _np, (anchor, positive, labels), {"l2_reg": float(l2_reg)})


def dice_loss(input, label, epsilon=1e-5, name=None):
    def _dice(p, l, eps):
        l_oh = jax.nn.one_hot(jnp.squeeze(l, -1).astype(jnp.int32), p.shape[-1], dtype=p.dtype)
        reduce_dims = tuple(range(1, p.ndim))
        inter = jnp.sum(p * l_oh, axis=reduce_dims)
        union = jnp.sum(p, axis=reduce_dims) + jnp.sum(l_oh, axis=reduce_dims)
        return jnp.mean(1 - (2 * inter + eps) / (union + eps))
    return D.apply("dice_loss", _dice, (input, label), {"eps": float(epsilon)})


def _hs(x, lab, w, b, pt, pc, num_classes):
    K = w.shape[0]
    l = lab.reshape(-1).astype(jnp.int32)
    if pt is None:
        c = l + num_classes                               # [N]
        # max path length: bits needed for 2*num_classes
        Lmax = max(int(num_classes - 1).bit_length(), 1)
        bits = jnp.arange(Lmax, dtype=jnp.int32)
        # floor(log2(c)) via vectorized find-last-set
        length = jnp.sum((c[:, None] >> (bits[None, :] + 1)) > 0,
                         axis=1)                          # [N]
        idx = (c[:, None] >> (bits[None, :] + 1)) - 1     # [N, L]
        bitv = ((c[:, None] >> bits[None, :]) & 1).astype(x.dtype)
        mask = (bits[None, :] < length[:, None]).astype(x.dtype)
    else:
        idx = pt.astype(jnp.int32)
        bitv = pc.astype(x.dtype)
        mask = (idx >= 0).astype(x.dtype)
    idx_safe = jnp.clip(idx, 0, K - 1)
    pre = jnp.einsum("nd,nld->nl", x, w[idx_safe],
                     preferred_element_type=jnp.float32).astype(x.dtype)
    if b is not None:
        pre = pre + b.reshape(-1)[idx_safe]
    pre = jnp.clip(pre, -40.0, 40.0)                      # ref clip
    loss_bits = jax.nn.softplus(pre) - bitv * pre
    return jnp.sum(loss_bits * mask, axis=-1, keepdims=True)


def hsigmoid_loss(input, label, num_classes, weight, bias=None, path_table=None,
                  path_code=None, is_sparse=False, name=None):
    """Hierarchical sigmoid loss (reference nn/functional/loss.py hsigmoid_loss
    over phi hsigmoid_loss kernel + matrix_bit_code.h SimpleCode/CustomCode).

    Default tree: binary-heap coding — for label l, c = l + num_classes,
    path length = floor(log2(c)), node index at bit k = (c >> (k+1)) - 1,
    bit value = (c >> k) & 1.  Loss per sample = sum over path bits of
    BCE-with-logits(w[idx]·x + b[idx], bit).  Custom tree: path_table /
    path_code rows (negative entries pad).  TPU formulation: the
    variable-length paths become a fixed [N, L] gather + mask, so the
    whole loss is one batched matvec (MXU) under jit.  is_sparse is a
    storage hint in the reference; dense gather here.  The impl functions
    are module-level so the dispatcher's executable cache hits.
    """
    if (path_table is None) != (path_code is None):
        raise ValueError(
            "hsigmoid_loss: path_table and path_code must be passed "
            "together (reference contract); got "
            f"path_table={'set' if path_table is not None else 'None'}, "
            f"path_code={'set' if path_code is not None else 'None'}")
    tensors = [input, label, weight]
    names = ["x", "lab", "w"]
    opt = {"b": bias, "pt": path_table, "pc": path_code}
    for k, v in opt.items():
        if v is not None:
            tensors.append(v)
            names.append(k)

    return D.apply("hsigmoid_loss", _hs_impl, tuple(tensors),
                   {"num_classes": int(num_classes), "names": tuple(names)})


def _hs_impl(*arrs, num_classes, names):
    # module-level (not a per-call closure) so the dispatcher's executable
    # cache hits; the optional-arg combination rides in via static `names`
    kw = dict(zip(names, arrs))
    return _hs(kw["x"], kw["lab"], kw["w"], kw.get("b"),
               kw.get("pt"), kw.get("pc"), num_classes)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    def _ctc(lp, lab, in_len, lab_len, blank, reduction):
        # lp: [T, N, C] logits (paddle convention) -> use optax-style CTC
        import optax
        # optax expects [N, T, C] log-probs and padded labels [N, L]
        logp = jax.nn.log_softmax(lp, axis=-1).transpose(1, 0, 2)
        n, t = logp.shape[0], logp.shape[1]
        logit_pad = (jnp.arange(t)[None, :] >= in_len[:, None]).astype(logp.dtype)
        lab_pad = (jnp.arange(lab.shape[1])[None, :] >= lab_len[:, None]).astype(logp.dtype)
        loss = optax.ctc_loss(logp, logit_pad, lab, lab_pad, blank_id=blank)
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lab_len.astype(loss.dtype), 1.0))
        return _reduce(loss, reduction)
    return D.apply("ctc_loss", _ctc, (log_probs, labels, input_lengths, label_lengths),
                   {"blank": int(blank), "reduction": reduction})
