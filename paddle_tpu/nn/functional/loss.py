"""Loss functionals.

Parity with /root/reference/python/paddle/nn/functional/loss.py.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core import dispatch as D
from ...core.tensor import Tensor

__all__ = [
    "cross_entropy", "softmax_with_cross_entropy", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "nll_loss", "l1_loss", "mse_loss",
    "smooth_l1_loss", "kl_div", "margin_ranking_loss", "hinge_embedding_loss",
    "cosine_embedding_loss", "triplet_margin_loss",
    "triplet_margin_with_distance_loss", "multi_label_soft_margin_loss",
    "soft_margin_loss", "sigmoid_focal_loss", "log_loss", "square_error_cost",
    "ctc_loss", "poisson_nll_loss", "gaussian_nll_loss", "hsigmoid_loss",
    "npair_loss", "dice_loss", "multi_margin_loss",
]


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False,
                               axis=-1, name=None):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    from .activation import softmax as _softmax
    from ...ops.manipulation import unsqueeze
    loss = unsqueeze(loss, axis)
    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


def triplet_margin_with_distance_loss(input, positive, negative, distance_function=None,
                                      margin=1.0, swap=False, reduction="mean", name=None):
    if distance_function is None:
        return triplet_margin_loss(input, positive, negative, margin=margin,
                                   swap=swap, reduction=reduction)
    d_pos = distance_function(input, positive)
    d_neg = distance_function(input, negative)
    if swap:
        from ..functional import common  # noqa
        d_pn = distance_function(positive, negative)
        from ...ops.math import minimum
        d_neg = minimum(d_neg, d_pn)
    from ...ops.math import add, subtract
    from ...ops.math import clip
    diff = clip(add(subtract(d_pos, d_neg), margin), min=0.0)
    from ...ops.math import mean as _mean, sum as _sum
    if reduction == "mean":
        return _mean(diff)
    if reduction == "sum":
        return _sum(diff)
    return diff


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    def _np(a, p, l, l2_reg):
        batch = a.shape[0]
        sim = a @ p.T
        eq = (l[:, None] == l[None, :]).astype(a.dtype)
        eq = eq / jnp.sum(eq, axis=1, keepdims=True)
        logp = jax.nn.log_softmax(sim, axis=1)
        ce = -jnp.mean(jnp.sum(eq * logp, axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, 1)) + jnp.mean(jnp.sum(p * p, 1))) * 0.25
        return ce + reg
    return D.apply("npair_loss", _np, (anchor, positive, labels), {"l2_reg": float(l2_reg)})


def _hs(x, lab, w, b, pt, pc, num_classes):
    K = w.shape[0]
    l = lab.reshape(-1).astype(jnp.int32)
    if pt is None:
        c = l + num_classes                               # [N]
        # max path length: bits needed for 2*num_classes
        Lmax = max(int(num_classes - 1).bit_length(), 1)
        bits = jnp.arange(Lmax, dtype=jnp.int32)
        # floor(log2(c)) via vectorized find-last-set
        length = jnp.sum((c[:, None] >> (bits[None, :] + 1)) > 0,
                         axis=1)                          # [N]
        idx = (c[:, None] >> (bits[None, :] + 1)) - 1     # [N, L]
        bitv = ((c[:, None] >> bits[None, :]) & 1).astype(x.dtype)
        mask = (bits[None, :] < length[:, None]).astype(x.dtype)
    else:
        idx = pt.astype(jnp.int32)
        bitv = pc.astype(x.dtype)
        mask = (idx >= 0).astype(x.dtype)
    idx_safe = jnp.clip(idx, 0, K - 1)
    pre = jnp.einsum("nd,nld->nl", x, w[idx_safe],
                     preferred_element_type=jnp.float32).astype(x.dtype)
    if b is not None:
        pre = pre + b.reshape(-1)[idx_safe]
    pre = jnp.clip(pre, -40.0, 40.0)                      # ref clip
    loss_bits = jax.nn.softplus(pre) - bitv * pre
    return jnp.sum(loss_bits * mask, axis=-1, keepdims=True)


def hsigmoid_loss(input, label, num_classes, weight, bias=None, path_table=None,
                  path_code=None, is_sparse=False, name=None):
    """Hierarchical sigmoid loss (reference nn/functional/loss.py hsigmoid_loss
    over phi hsigmoid_loss kernel + matrix_bit_code.h SimpleCode/CustomCode).

    Default tree: binary-heap coding — for label l, c = l + num_classes,
    path length = floor(log2(c)), node index at bit k = (c >> (k+1)) - 1,
    bit value = (c >> k) & 1.  Loss per sample = sum over path bits of
    BCE-with-logits(w[idx]·x + b[idx], bit).  Custom tree: path_table /
    path_code rows (negative entries pad).  TPU formulation: the
    variable-length paths become a fixed [N, L] gather + mask, so the
    whole loss is one batched matvec (MXU) under jit.  is_sparse is a
    storage hint in the reference; dense gather here.  The impl functions
    are module-level so the dispatcher's executable cache hits.
    """
    if (path_table is None) != (path_code is None):
        raise ValueError(
            "hsigmoid_loss: path_table and path_code must be passed "
            "together (reference contract); got "
            f"path_table={'set' if path_table is not None else 'None'}, "
            f"path_code={'set' if path_code is not None else 'None'}")
    tensors = [input, label, weight]
    names = ["x", "lab", "w"]
    opt = {"b": bias, "pt": path_table, "pc": path_code}
    for k, v in opt.items():
        if v is not None:
            tensors.append(v)
            names.append(k)

    return D.apply("hsigmoid_loss", _hs_impl, tuple(tensors),
                   {"num_classes": int(num_classes), "names": tuple(names)})


def _hs_impl(*arrs, num_classes, names):
    # module-level (not a per-call closure) so the dispatcher's executable
    # cache hits; the optional-arg combination rides in via static `names`
    kw = dict(zip(names, arrs))
    return _hs(kw["x"], kw["lab"], kw["w"], kw.get("b"),
               kw.get("pt"), kw.get("pc"), num_classes)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    def _ctc(lp, lab, in_len, lab_len, blank, reduction):
        # lp: [T, N, C] logits (paddle convention) -> use optax-style CTC
        import optax
        # optax expects [N, T, C] log-probs and padded labels [N, L]
        logp = jax.nn.log_softmax(lp, axis=-1).transpose(1, 0, 2)
        n, t = logp.shape[0], logp.shape[1]
        logit_pad = (jnp.arange(t)[None, :] >= in_len[:, None]).astype(logp.dtype)
        lab_pad = (jnp.arange(lab.shape[1])[None, :] >= lab_len[:, None]).astype(logp.dtype)
        loss = optax.ctc_loss(logp, logit_pad, lab, lab_pad, blank_id=blank)
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lab_len.astype(loss.dtype), 1.0))
        return _reduce(loss, reduction)
    return D.apply("ctc_loss", _ctc, (log_probs, labels, input_lengths, label_lengths),
                   {"blank": int(blank), "reduction": reduction})


# kernel-driven (generated from ops.yaml `kernel:` over ops/kernels.py;
# oracle-checked by tests/test_loss_oracle.py)
from ...ops.generated.op_wrappers import (  # noqa: E402,F401
    binary_cross_entropy,
    binary_cross_entropy_with_logits,
    cosine_embedding_loss,
    cross_entropy,
    dice_loss,
    gaussian_nll_loss,
    hinge_embedding_loss,
    kl_div,
    l1_loss,
    log_loss,
    margin_ranking_loss,
    mse_loss,
    multi_label_soft_margin_loss,
    multi_margin_loss,
    nll_loss,
    poisson_nll_loss,
    sigmoid_focal_loss,
    smooth_l1_loss,
    soft_margin_loss,
    square_error_cost,
    triplet_margin_loss,
)
