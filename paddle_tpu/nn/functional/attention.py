"""Attention functionals.

Parity with /root/reference/python/paddle/nn/functional/flash_attention.py
(flash_attention :358, scaled_dot_product_attention :1139).  The default path
is a jnp composition XLA fuses well; when FLAGS_use_pallas_kernels is on and
shapes qualify, the Pallas flash kernel (paddle_tpu/ops/pallas/flash_attention.py)
is used instead — the TPU analog of the reference's FA2 CUDA kernel.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core import dispatch as D
from ...core.flags import get_flag

__all__ = ["scaled_dot_product_attention", "flash_attention",
           "flash_attn_unpadded", "sdp_kernel"]


from ...ops.pallas.flash_attention import _repeat_kv


def _sdpa_ref(q, k, v, *rest, causal, dropout_p, scale, has_mask):
    # q/k/v: [B, S, H, D] (paddle flash-attention layout); GQA when
    # k/v carry fewer heads (reference flash_attention.py GQA path)
    mask = rest[0] if has_mask else None
    group = q.shape[2] // k.shape[2]
    k, v = _repeat_kv(k, group), _repeat_kv(v, group)
    qt = jnp.swapaxes(q, 1, 2)  # [B, H, S, D]
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * s
    scores = scores.astype(jnp.float32)
    if causal:
        ql, kl = scores.shape[-2], scores.shape[-1]
        causal_mask = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        scores = jnp.where(causal_mask, scores, -jnp.inf)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            scores = jnp.where(mask, scores, -jnp.inf)
        else:
            scores = scores + mask.astype(scores.dtype)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
    return jnp.swapaxes(out, 1, 2)


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, name=None):
    """Inputs [batch, seq, num_heads, head_dim] like the reference."""
    use_pallas = get_flag("use_pallas_kernels")
    if use_pallas and attn_mask is None and dropout_p == 0.0:
        from ...ops.pallas.flash_attention import flash_attention_fwd
        if flash_attention_fwd.supports(query.shape, query.dtype.name,
                                        tuple(key.shape), bool(is_causal)):
            return D.apply(
                "flash_attention", flash_attention_fwd,
                (query, key, value), {"causal": bool(is_causal)})
    args = (query, key, value) + ((attn_mask,) if attn_mask is not None else ())
    return D.apply("sdpa", _sdpa_ref, args,
                   {"causal": bool(is_causal), "dropout_p": float(dropout_p),
                    "scale": None, "has_mask": attn_mask is not None})


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None):
    out = scaled_dot_product_attention(query, key, value, None, dropout, causal,
                                       training)
    if return_softmax:
        return out, None
    return out, None


def _unpadded_impl(q, k, v, cu_q, cu_k, *, scale, causal):
    # q/k/v: [total_tokens, heads, dim]; sequences are concatenated and
    # delimited by cu_seqlens (reference flash_attn_unpadded :756).
    group = q.shape[1] // k.shape[1]
    if group > 1:  # 3-D [T, Hk, D]: reuse the shared 4-D helper
        k = _repeat_kv(k[None], group)[0]
        v = _repeat_kv(v[None], group)[0]
    tq, tk = q.shape[0], k.shape[0]
    pos_q = jnp.arange(tq)
    pos_k = jnp.arange(tk)
    # segment id = index of the containing [cu[i], cu[i+1]) interval
    seg_q = jnp.searchsorted(cu_q, pos_q, side="right") - 1
    seg_k = jnp.searchsorted(cu_k, pos_k, side="right") - 1
    rel_q = pos_q - cu_q[seg_q]          # position within own sequence
    rel_k = pos_k - cu_k[seg_k]
    mask = seg_q[:, None] == seg_k[None, :]
    if causal:
        mask = mask & (rel_q[:, None] >= rel_k[None, :])
    scores = jnp.einsum("qhd,khd->hqk",
                        q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    scores = jnp.where(mask[None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    # rows whose sequence is empty are all -inf -> nan; zero them
    probs = jnp.where(mask[None], probs, 0.0)
    out = jnp.einsum("hqk,khd->qhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale, dropout=0.0,
                        causal=False, return_softmax=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """Varlen attention over concatenated sequences
    (reference nn/functional/flash_attention.py:756).

    Inputs are [total_tokens, num_heads, head_dim] with `cu_seqlens_*`
    holding cumulative sequence offsets (len = batch+1).  Dispatch: the
    segment-aware Pallas varlen kernel family
    (ops/pallas/flash_attention_varlen.py — true flash memory behavior,
    O(sum s_i^2) compute via per-block kv ranges) when it provably lowers
    on this backend, else the segment-masked XLA composition.
    """
    if dropout and training:
        raise NotImplementedError(
            "flash_attn_unpadded: attention dropout is not implemented; "
            "pass dropout=0.0")
    from ...ops.pallas.flash_attention_varlen import (_varlen_attention,
                                                      use_varlen_flash)
    import jax as _jax

    q_arr = query._data if hasattr(query, "_data") else query
    k_arr = key._data if hasattr(key, "_data") else key
    # probe the dtype the kernel will ACTUALLY run in: the dispatcher
    # autocasts float inputs per AMP state, so under O2 an fp32 input
    # executes as bf16 — probing the pre-cast dtype would cache a compile
    # the real call never uses and skip the promised fallback
    from ...core.dispatch import amp_state
    cast_to = amp_state.autocast_dtype_for("flash_attn_unpadded")
    eff_dtype = cast_to if cast_to is not None else q_arr.dtype
    q_probe = _jax.ShapeDtypeStruct(q_arr.shape, eff_dtype)
    k_probe = _jax.ShapeDtypeStruct(k_arr.shape, eff_dtype)
    if use_varlen_flash(q_probe, k_probe, bool(causal)):
        out = D.apply(
            "flash_attn_unpadded",
            lambda q, k, v, cq, ck, scale, causal: _varlen_attention(
                causal, scale, q, k, v, cq, ck),
            (query, key, value, cu_seqlens_q, cu_seqlens_k),
            {"scale": float(scale), "causal": bool(causal)})
        return out, None
    out = D.apply("flash_attn_unpadded", _unpadded_impl,
                  (query, key, value, cu_seqlens_q, cu_seqlens_k),
                  {"scale": float(scale), "causal": bool(causal)})
    return out, None


class sdp_kernel:
    """Context manager selecting attention backends (API compat)."""

    def __init__(self, enable_flash=True, enable_math=True, enable_mem_efficient=True):
        self.enable_flash = enable_flash

    def __enter__(self):
        from ...core.flags import set_flags
        self._prev = get_flag("use_pallas_kernels")
        set_flags({"use_pallas_kernels": self.enable_flash})
        return self

    def __exit__(self, *exc):
        from ...core.flags import set_flags
        set_flags({"use_pallas_kernels": self._prev})
        return False
