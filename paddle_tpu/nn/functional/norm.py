"""Normalization functionals.

Parity with /root/reference/python/paddle/nn/functional/norm.py (layer_norm,
batch_norm, instance_norm, group_norm, local_response_norm) plus rms_norm
(reference exposes fused_rms_norm in incubate:
/root/reference/python/paddle/incubate/nn/functional/fused_rms_norm.py).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core import dispatch as D
from ...core.tensor import Tensor

__all__ = ["layer_norm", "batch_norm", "instance_norm", "group_norm",
           "local_response_norm", "rms_norm"]


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05, name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    n_axes = len(tuple(normalized_shape))

    def _ln(a, *wb, n_axes, eps, has_w, has_b):
        axes = tuple(range(a.ndim - n_axes, a.ndim))
        # reduce in f32 for numeric parity with the reference's fused kernel
        af = a.astype(jnp.float32) if a.dtype in (jnp.float16, jnp.bfloat16) else a
        mean = jnp.mean(af, axis=axes, keepdims=True)
        var = jnp.mean(jnp.square(af - mean), axis=axes, keepdims=True)
        out = (af - mean) * jax.lax.rsqrt(var + eps)
        i = 0
        if has_w:
            out = out * wb[i].astype(out.dtype); i += 1
        if has_b:
            out = out + wb[i].astype(out.dtype)
        return out.astype(a.dtype)

    args = [x]
    if weight is not None:
        args.append(weight)
    if bias is not None:
        args.append(bias)
    return D.apply("layer_norm", _ln, tuple(args),
                   {"n_axes": n_axes, "eps": float(epsilon),
                    "has_w": weight is not None, "has_b": bias is not None})


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    def _rms(a, *w, eps, has_w):
        af = a.astype(jnp.float32) if a.dtype in (jnp.float16, jnp.bfloat16) else a
        ms = jnp.mean(jnp.square(af), axis=-1, keepdims=True)
        out = af * jax.lax.rsqrt(ms + eps)
        if has_w:
            out = out * w[0].astype(out.dtype)
        return out.astype(a.dtype)
    args = (x, weight) if weight is not None else (x,)
    return D.apply("rms_norm", _rms, args, {"eps": float(epsilon), "has_w": weight is not None})


def _bn_stats_axes(ndim, data_format):
    ch_axis = 1 if (data_format.startswith("NC") or data_format == "NCHW") else ndim - 1
    axes = tuple(i for i in range(ndim) if i != ch_axis)
    return ch_axis, axes


def batch_norm(x, running_mean, running_var, weight=None, bias=None, training=False,
               momentum=0.9, epsilon=1e-05, data_format="NCHW", use_global_stats=None,
               name=None):
    ch_axis, axes = _bn_stats_axes(x.ndim, data_format)
    use_batch_stats = training and not use_global_stats

    if use_batch_stats:
        # compute batch stats (also used to update running buffers eagerly)
        def _stats(a, axes):
            m = jnp.mean(a, axis=axes)
            v = jnp.var(a, axis=axes)
            return m, v
        from ...core import dispatch
        with dispatch.no_grad():
            bm, bv = D.apply("bn_stats", _stats, (x.detach(),), {"axes": axes})
        if running_mean is not None:
            running_mean._data = (momentum * running_mean._data
                                  + (1.0 - momentum) * bm._data.astype(running_mean._data.dtype))
            running_var._data = (momentum * running_var._data
                                 + (1.0 - momentum) * bv._data.astype(running_var._data.dtype))

        def _bn_train(a, *wb, axes, ch_axis, eps, has_w, has_b):
            m = jnp.mean(a, axis=axes, keepdims=True)
            v = jnp.var(a, axis=axes, keepdims=True)
            out = (a - m) * jax.lax.rsqrt(v + eps)
            shape = [1] * a.ndim
            shape[ch_axis] = a.shape[ch_axis]
            i = 0
            if has_w:
                out = out * wb[i].reshape(shape); i += 1
            if has_b:
                out = out + wb[i].reshape(shape)
            return out
        args = [x]
        if weight is not None:
            args.append(weight)
        if bias is not None:
            args.append(bias)
        return D.apply("batch_norm_train", _bn_train, tuple(args),
                       {"axes": axes, "ch_axis": ch_axis, "eps": float(epsilon),
                        "has_w": weight is not None, "has_b": bias is not None})

    def _bn_eval(a, rm, rv, *wb, ch_axis, eps, has_w, has_b):
        shape = [1] * a.ndim
        shape[ch_axis] = a.shape[ch_axis]
        out = (a - rm.reshape(shape)) * jax.lax.rsqrt(rv.reshape(shape) + eps)
        i = 0
        if has_w:
            out = out * wb[i].reshape(shape); i += 1
        if has_b:
            out = out + wb[i].reshape(shape)
        return out
    args = [x, running_mean, running_var]
    if weight is not None:
        args.append(weight)
    if bias is not None:
        args.append(bias)
    return D.apply("batch_norm_eval", _bn_eval, tuple(args),
                   {"ch_axis": ch_axis, "eps": float(epsilon),
                    "has_w": weight is not None, "has_b": bias is not None})


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-05,
                  data_format="NCHW", name=None):
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    axes = tuple(i for i in range(x.ndim) if i not in (0, ch_axis))

    def _in(a, *wb, axes, ch_axis, eps, has_w, has_b):
        m = jnp.mean(a, axis=axes, keepdims=True)
        v = jnp.var(a, axis=axes, keepdims=True)
        out = (a - m) * jax.lax.rsqrt(v + eps)
        shape = [1] * a.ndim
        shape[ch_axis] = a.shape[ch_axis]
        i = 0
        if has_w:
            out = out * wb[i].reshape(shape); i += 1
        if has_b:
            out = out + wb[i].reshape(shape)
        return out
    args = [x]
    if weight is not None:
        args.append(weight)
    if bias is not None:
        args.append(bias)
    return D.apply("instance_norm", _in, tuple(args),
                   {"axes": axes, "ch_axis": ch_axis, "eps": float(eps),
                    "has_w": weight is not None, "has_b": bias is not None})


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None,
               data_format="NCHW", name=None):
    channels_last = data_format.endswith("C") and data_format != "NC"

    def _gn(a, *wb, g, eps, channels_last, has_w, has_b):
        if channels_last:
            a_t = jnp.moveaxis(a, -1, 1)
        else:
            a_t = a
        n, c = a_t.shape[0], a_t.shape[1]
        rest = a_t.shape[2:]
        grouped = a_t.reshape(n, g, c // g, *rest)
        axes = tuple(range(2, grouped.ndim))
        m = jnp.mean(grouped, axis=axes, keepdims=True)
        v = jnp.var(grouped, axis=axes, keepdims=True)
        out = ((grouped - m) * jax.lax.rsqrt(v + eps)).reshape(a_t.shape)
        shape = [1] * a_t.ndim
        shape[1] = c
        i = 0
        if has_w:
            out = out * wb[i].reshape(shape); i += 1
        if has_b:
            out = out + wb[i].reshape(shape)
        if channels_last:
            out = jnp.moveaxis(out, 1, -1)
        return out
    args = [x]
    if weight is not None:
        args.append(weight)
    if bias is not None:
        args.append(bias)
    return D.apply("group_norm", _gn, tuple(args),
                   {"g": int(num_groups), "eps": float(epsilon),
                    "channels_last": channels_last,
                    "has_w": weight is not None, "has_b": bias is not None})


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def _lrn(a, size, alpha, beta, k, channels_last):
        if channels_last:
            a_t = jnp.moveaxis(a, -1, 1)
        else:
            a_t = a
        sq = jnp.square(a_t)
        c = a_t.shape[1]
        pad_lo = (size - 1) // 2
        pad_hi = size - 1 - pad_lo
        padded = jnp.pad(sq, [(0, 0), (pad_lo, pad_hi)] + [(0, 0)] * (a_t.ndim - 2))
        acc = jnp.zeros_like(a_t)
        for i in range(size):
            acc = acc + jax.lax.slice_in_dim(padded, i, i + c, axis=1)
        div = jnp.power(k + alpha * acc / size, beta)
        out = a_t / div
        if channels_last:
            out = jnp.moveaxis(out, 1, -1)
        return out
    return D.apply("local_response_norm", _lrn, (x,),
                   {"size": int(size), "alpha": float(alpha), "beta": float(beta),
                    "k": float(k), "channels_last": data_format.endswith("C")})
