"""Spatial-sampling functionals (reference python/paddle/nn/functional/
vision.py: grid_sample, affine_grid + pooling.py max_unpool2d)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core import dispatch as D

__all__ = ["grid_sample", "affine_grid", "max_unpool2d"]


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Sample x [N, C, H, W] at normalized grid [N, Ho, Wo, 2] locations
    (reference nn/functional/vision.py grid_sample; kernel
    phi/kernels/gpu/grid_sample_kernel.cu)."""
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"mode must be bilinear|nearest, got {mode!r}")
    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError(f"bad padding_mode {padding_mode!r}")

    def impl(x, grid, mode, padding_mode, align):
        N, C, H, W = x.shape
        g = grid.astype(jnp.float32)
        gx, gy = g[..., 0], g[..., 1]
        if align:
            fx = (gx + 1) * 0.5 * (W - 1)
            fy = (gy + 1) * 0.5 * (H - 1)
        else:
            fx = ((gx + 1) * W - 1) * 0.5
            fy = ((gy + 1) * H - 1) * 0.5

        def reflect(v, lo, hi):
            rng = hi - lo
            if rng <= 0:
                return jnp.zeros_like(v)
            v = jnp.abs(v - lo) % (2 * rng)
            return lo + jnp.where(v > rng, 2 * rng - v, v)

        if padding_mode == "reflection":
            if align:
                fx = reflect(fx, 0.0, W - 1.0)
                fy = reflect(fy, 0.0, H - 1.0)
            else:
                fx = jnp.clip(reflect(fx, -0.5, W - 0.5), 0, W - 1)
                fy = jnp.clip(reflect(fy, -0.5, H - 0.5), 0, H - 1)

        def fetch(ix, iy):
            # [N, Ho, Wo] int coords -> [N, C, Ho, Wo] values (+valid mask)
            inb = ((ix >= 0) & (ix <= W - 1) & (iy >= 0) & (iy <= H - 1))
            ixc = jnp.clip(ix, 0, W - 1).astype(jnp.int32)
            iyc = jnp.clip(iy, 0, H - 1).astype(jnp.int32)
            vals = jax.vmap(
                lambda img, yy, xx: img[:, yy, xx])(x, iyc, ixc)
            if padding_mode == "zeros":
                vals = vals * inb[:, None].astype(vals.dtype)
            return vals

        if mode == "nearest":
            return fetch(jnp.round(fx), jnp.round(fy)).astype(x.dtype)

        x0 = jnp.floor(fx)
        y0 = jnp.floor(fy)
        wx = (fx - x0)[:, None]
        wy = (fy - y0)[:, None]
        out = (fetch(x0, y0) * (1 - wx) * (1 - wy)
               + fetch(x0 + 1, y0) * wx * (1 - wy)
               + fetch(x0, y0 + 1) * (1 - wx) * wy
               + fetch(x0 + 1, y0 + 1) * wx * wy)
        return out.astype(x.dtype)

    return D.apply("grid_sample", impl, (x, grid),
                   {"mode": str(mode), "padding_mode": str(padding_mode),
                    "align": bool(align_corners)})


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """2-D affine sampling grid from theta [N, 2, 3]
    (reference nn/functional/vision.py affine_grid)."""
    if hasattr(out_shape, "tolist"):
        out_shape = [int(v) for v in out_shape.tolist()]
    N, C, H, W = (int(v) for v in out_shape)

    def impl(theta, H, W, align):
        th = theta.astype(jnp.float32)
        if align:
            ys = jnp.linspace(-1.0, 1.0, H)
            xs = jnp.linspace(-1.0, 1.0, W)
        else:
            ys = (jnp.arange(H) * 2 + 1) / H - 1
            xs = (jnp.arange(W) * 2 + 1) / W - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)       # [H, W, 3]
        out = jnp.einsum("hwk,nck->nhwc", base, th)     # [N, H, W, 2]
        return out.astype(theta.dtype)

    return D.apply("affine_grid", impl, (theta,),
                   {"H": H, "W": W, "align": bool(align_corners)})


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    """Invert max_pool2d using the saved flat indices (reference
    nn/functional/pooling.py max_unpool2d)."""
    if data_format != "NCHW":
        raise ValueError("max_unpool2d supports NCHW only")
    ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
        else tuple(kernel_size)
    st = ks if stride is None else (
        (stride, stride) if isinstance(stride, int) else tuple(stride))
    pd = (padding, padding) if isinstance(padding, int) else tuple(padding)

    def impl(x, idx, ks, st, pd, out_hw):
        N, C, H, W = x.shape
        if out_hw is None:
            Ho = (H - 1) * st[0] - 2 * pd[0] + ks[0]
            Wo = (W - 1) * st[1] - 2 * pd[1] + ks[1]
        else:
            Ho, Wo = out_hw
        flat = jnp.zeros((N, C, Ho * Wo), x.dtype)
        out = jax.vmap(jax.vmap(
            lambda dst, src, ii: dst.at[ii.reshape(-1)].set(
                src.reshape(-1))))(flat, x, idx.astype(jnp.int32))
        return out.reshape(N, C, Ho, Wo)

    out_hw = None
    if output_size is not None:
        out_hw = tuple(int(v) for v in output_size[-2:])
    return D.apply("max_unpool2d", impl, (x, indices),
                   {"ks": ks, "st": st, "pd": pd, "out_hw": out_hw})
