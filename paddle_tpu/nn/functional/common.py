"""Common functionals: linear, dropout, embedding, interpolate, padding, etc.

Parity with /root/reference/python/paddle/nn/functional/{common,input}.py.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core import dispatch as D
from ...core import random_state
from ...core.tensor import Tensor

__all__ = [
    "linear", "dropout", "dropout2d", "dropout3d", "alpha_dropout", "embedding",
    "one_hot", "label_smooth", "pad", "interpolate", "upsample", "pixel_shuffle",
    "pixel_unshuffle", "channel_shuffle", "unfold", "fold", "cosine_similarity",
    "bilinear", "normalize", "zeropad2d",
]


def _linear(x, w, b):
    y = jnp.matmul(x, w)
    if b is not None:
        y = y + b
    return y


def linear(x, weight, bias=None, name=None):
    """y = x @ W (+ b).  Weight layout [in, out] per the reference
    (/root/reference/python/paddle/nn/layer/common.py Linear)."""
    if bias is None:
        return D.apply("linear", lambda a, w: jnp.matmul(a, w), (x, weight))
    return D.apply("linear", _linear, (x, weight, bias))


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if isinstance(p, Tensor):
        p = float(p.item())
    if not training or p == 0.0:
        if not training and mode == "downscale_in_infer" and p > 0.0:
            from ...ops.math import scale as _scale
            return _scale(x, 1.0 - p)
        return x
    key = random_state.next_key()
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else (None if axis is None else (int(axis),))

    def _dropout(k, a, p, axis, upscale):
        shape = a.shape if axis is None else tuple(
            a.shape[i] if i in axis else 1 for i in range(a.ndim))
        keep = jax.random.bernoulli(k, 1.0 - p, shape)
        if upscale:
            return jnp.where(keep, a / (1.0 - p), jnp.zeros((), a.dtype)).astype(a.dtype)
        return jnp.where(keep, a, jnp.zeros((), a.dtype))
    return D.apply("dropout", _dropout, (key, x),
                   {"p": float(p), "axis": ax, "upscale": mode == "upscale_in_train"})


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    if not training or p == 0.0:
        return x
    axis = (0, 1) if data_format == "NCHW" else (0, 3)
    return dropout(x, p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    if not training or p == 0.0:
        return x
    axis = (0, 1) if data_format == "NCDHW" else (0, 4)
    return dropout(x, p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    key = random_state.next_key()

    def _ad(k, a, p):
        alpha = 1.6732632423543772
        scale = 1.0507009873554805
        alpha_p = -alpha * scale
        keep = jax.random.bernoulli(k, 1.0 - p, a.shape)
        A = (1.0 - p + p * alpha_p ** 2) ** -0.5
        B = -A * p * alpha_p
        return (A * jnp.where(keep, a, jnp.asarray(alpha_p, a.dtype)) + B).astype(a.dtype)
    return D.apply("alpha_dropout", _ad, (key, x), {"p": float(p)})


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    def _emb(ids, w, padding_idx):
        out = jnp.take(w, ids, axis=0)
        if padding_idx is not None:
            # padding rows contribute no gradient to the table (reference
            # embedding_grad zeroes the padding_idx row)
            pad = (ids == padding_idx)[..., None]
            out = jnp.where(pad, jax.lax.stop_gradient(out), out)
        return out
    return D.apply("embedding", _emb, (x, weight), {"padding_idx": padding_idx})


def one_hot(x, num_classes, name=None):
    from ...ops.manipulation import one_hot as _oh
    return _oh(x, num_classes)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def _ls(l, epsilon):
        n = l.shape[-1]
        return (1.0 - epsilon) * l + epsilon / n
    if prior_dist is not None:
        return D.apply("label_smooth_p",
                       lambda l, pd, epsilon: (1.0 - epsilon) * l + epsilon * pd,
                       (label, prior_dist), {"epsilon": float(epsilon)})
    return D.apply("label_smooth", _ls, (label,), {"epsilon": float(epsilon)})


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW",
        pad_from_left_axis=True, name=None):
    from ...ops.manipulation import pad as _pad
    return _pad(x, pad, mode, value, data_format, pad_from_left_axis)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW", name=None):
    nd = x.ndim - 2
    if data_format.endswith("C"):
        spatial = tuple(x.shape[1:1 + nd])
    else:
        spatial = tuple(x.shape[2:])
    if size is not None:
        if isinstance(size, Tensor):
            size = size.tolist()
        out_size = tuple(int(s.item()) if isinstance(s, Tensor) else int(s) for s in size)
    else:
        if isinstance(scale_factor, (int, float)):
            scale_factor = [scale_factor] * nd
        out_size = tuple(int(s * f) for s, f in zip(spatial, scale_factor))

    if mode == "area":
        # area interpolation == adaptive average pooling (reference maps it
        # the same way; torch 'area' likewise)
        from .pooling import (adaptive_avg_pool1d, adaptive_avg_pool2d,
                              adaptive_avg_pool3d)
        pool = {1: adaptive_avg_pool1d, 2: adaptive_avg_pool2d,
                3: adaptive_avg_pool3d}[nd]
        if data_format.endswith("C"):
            raise NotImplementedError("area interpolate with channels-last; "
                                      "transpose to NC* first")
        return pool(x, out_size)

    # nearest / linear / cubic: gather-based separable resample honoring
    # the reference's align_corners / align_mode conventions
    # (reference interpolate kernels: align_corners=True ->
    # src = d*(in-1)/(out-1); align_mode 0 -> half-pixel; align_mode 1 ->
    # src = d*scale; nearest w/o corners -> floor(d*in/out), the legacy
    # asymmetric map; cubic uses the Keys kernel A=-0.75)
    def _resample(a, out_size, mode, align_corners, align_mode,
                  channels_last):
        nd_ = len(out_size)
        axes = (tuple(range(1, 1 + nd_)) if channels_last
                else tuple(range(2, 2 + nd_)))
        out_dtype = a.dtype
        if jnp.issubdtype(a.dtype, jnp.floating) and a.dtype != jnp.float32:
            a = a.astype(jnp.float32)   # half dtypes blend in f32 (reference)
        for ax, out_s in zip(axes, out_size):
            in_s = a.shape[ax]
            d = jnp.arange(out_s, dtype=jnp.float32)
            if mode == "nearest":
                if align_corners:
                    idx = jnp.round(d * (in_s - 1) / max(out_s - 1, 1))
                else:
                    idx = jnp.floor(d * in_s / out_s)
                a = jnp.take(a, jnp.clip(idx, 0, in_s - 1).astype(jnp.int32),
                             axis=ax)
                continue
            if align_corners:
                src = d * (in_s - 1) / max(out_s - 1, 1)
            elif align_mode == 1 and mode != "bicubic":
                src = d * in_s / out_s
            else:                        # half-pixel centers
                src = (d + 0.5) * in_s / out_s - 0.5
            wshape = [1] * a.ndim
            wshape[ax] = out_s

            def _tap(idx):
                return jnp.take(a, jnp.clip(idx, 0, in_s - 1), axis=ax)
            if mode == "bicubic":
                lo = jnp.floor(src).astype(jnp.int32)
                t_ = (src - lo).reshape(wshape)
                A = -0.75                 # Keys kernel (reference + torch)

                def k1(t):               # |t| <= 1
                    return ((A + 2) * t - (A + 3)) * t * t + 1

                def k2(t):               # 1 < |t| < 2
                    return ((A * t - 5 * A) * t + 8 * A) * t - 4 * A
                a = (_tap(lo - 1) * k2(t_ + 1) + _tap(lo) * k1(t_)
                     + _tap(lo + 1) * k1(1 - t_) + _tap(lo + 2) * k2(2 - t_))
                continue
            src = jnp.clip(src, 0.0, in_s - 1)
            lo = jnp.floor(src).astype(jnp.int32)
            w = (src - lo).reshape(wshape)
            a = _tap(lo) * (1 - w) + _tap(lo + 1) * w
        return a.astype(out_dtype)
    return D.apply("interpolate", _resample, (x,),
                   {"out_size": out_size, "mode": mode,
                    "align_corners": bool(align_corners),
                    "align_mode": int(align_mode),
                    "channels_last": data_format.endswith("C")})


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    def _ps(a, r, data_format):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c // (r * r), r, r, h, w)
            a = a.transpose(0, 1, 4, 2, 5, 3)
            return a.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = a.shape
        a = a.reshape(n, h, w, r, r, c // (r * r))
        a = a.transpose(0, 1, 3, 2, 4, 5)
        return a.reshape(n, h * r, w * r, c // (r * r))
    return D.apply("pixel_shuffle", _ps, (x,),
                   {"r": int(upscale_factor), "data_format": data_format})


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    def _pu(a, r, data_format):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c, h // r, r, w // r, r)
            a = a.transpose(0, 1, 3, 5, 2, 4)
            return a.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = a.shape
        a = a.reshape(n, h // r, r, w // r, r, c)
        a = a.transpose(0, 1, 3, 2, 4, 5)
        return a.reshape(n, h // r, w // r, c * r * r)
    return D.apply("pixel_unshuffle", _pu, (x,),
                   {"r": int(downscale_factor), "data_format": data_format})


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def _cs(a, g, data_format):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            return a.reshape(n, g, c // g, h, w).transpose(0, 2, 1, 3, 4).reshape(n, c, h, w)
        n, h, w, c = a.shape
        return a.reshape(n, h, w, g, c // g).transpose(0, 1, 2, 4, 3).reshape(n, h, w, c)
    return D.apply("channel_shuffle", _cs, (x,),
                   {"g": int(groups), "data_format": data_format})


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    def tup(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    k, s, d = tup(kernel_sizes), tup(strides), tup(dilations)
    p = paddings
    if isinstance(p, int):
        p = (p, p, p, p)
    elif len(p) == 2:
        p = (p[0], p[0], p[1], p[1])

    def _unfold(a, k, s, p, d):
        n, c, h, w = a.shape
        a = jnp.pad(a, ((0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])))
        out_h = (a.shape[2] - (d[0] * (k[0] - 1) + 1)) // s[0] + 1
        out_w = (a.shape[3] - (d[1] * (k[1] - 1) + 1)) // s[1] + 1
        patches = jax.lax.conv_general_dilated_patches(
            a, filter_shape=k, window_strides=s, padding="VALID", rhs_dilation=d,
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        return patches.reshape(n, c * k[0] * k[1], out_h * out_w)
    return D.apply("unfold", _unfold, (x,), {"k": k, "s": s, "p": tuple(p), "d": d})


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    def tup(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    out_size, k, s, d = tup(output_sizes), tup(kernel_sizes), tup(strides), tup(dilations)
    p = paddings
    if isinstance(p, int):
        p = (p, p, p, p)
    elif len(p) == 2:
        p = (p[0], p[0], p[1], p[1])

    def _fold(a, out_size, k, s, p, d):
        n, ckk, L = a.shape
        c = ckk // (k[0] * k[1])
        h_p = out_size[0] + p[0] + p[1]
        w_p = out_size[1] + p[2] + p[3]
        out_h = (h_p - (d[0] * (k[0] - 1) + 1)) // s[0] + 1
        out_w = (w_p - (d[1] * (k[1] - 1) + 1)) // s[1] + 1
        a = a.reshape(n, c, k[0], k[1], out_h, out_w)
        out = jnp.zeros((n, c, h_p, w_p), a.dtype)
        for i in range(k[0]):
            for j in range(k[1]):
                out = out.at[:, :, i * d[0]:i * d[0] + out_h * s[0]:s[0],
                             j * d[1]:j * d[1] + out_w * s[1]:s[1]].add(a[:, :, i, j])
        return out[:, :, p[0]:h_p - p[1], p[2]:w_p - p[3]]
    return D.apply("fold", _fold, (x,),
                   {"out_size": out_size, "k": k, "s": s, "p": tuple(p), "d": d})


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def _cs(a, b, axis, eps):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.sqrt(jnp.sum(a * a, axis=axis))
        nb = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return dot / jnp.maximum(na * nb, eps)
    return D.apply("cosine_similarity", _cs, (x1, x2), {"axis": int(axis), "eps": float(eps)})


def bilinear(x1, x2, weight, bias=None, name=None):
    def _bl(a, b, w, bias):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if bias is not None:
            out = out + bias
        return out
    if bias is None:
        return D.apply("bilinear", lambda a, b, w: jnp.einsum("bi,oij,bj->bo", a, w, b),
                       (x1, x2, weight))
    return D.apply("bilinear", _bl, (x1, x2, weight, bias))


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def _norm(a, p, axis, eps):
        n = jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return a / jnp.maximum(n, eps)
    return D.apply("normalize", _norm, (x,),
                   {"p": float(p), "axis": int(axis), "eps": float(epsilon)})
