"""Activation functionals.

Parity with /root/reference/python/paddle/nn/functional/activation.py.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core import dispatch as D

__all__ = [
    "relu", "relu_", "relu6", "elu", "selu", "celu", "gelu", "silu", "swish",
    "sigmoid", "hardsigmoid", "hardswish", "hardtanh", "hardshrink",
    "softshrink", "tanhshrink", "leaky_relu", "log_sigmoid", "log_softmax",
    "softmax", "softmax_", "softplus", "softsign", "mish", "maxout", "prelu",
    "rrelu", "thresholded_relu", "glu", "gumbel_softmax", "tanh", "tanh_",
]


def _un(name, jfn, **static):
    def op(x, name=None, **kw):
        s = dict(static)
        s.update({k: v for k, v in kw.items() if k in static})
        return D.apply(op_name, jfn, (x,), s) if s else D.apply(op_name, jfn, (x,))
    op_name = name
    op.__name__ = name
    return op


relu = _un("relu", jax.nn.relu)
relu6 = _un("relu6", jax.nn.relu6)
sigmoid = _un("sigmoid", jax.nn.sigmoid)
silu = _un("silu", jax.nn.silu)
softsign = _un("softsign", jax.nn.soft_sign)
tanh = _un("tanh", jnp.tanh)
log_sigmoid = _un("log_sigmoid", jax.nn.log_sigmoid)
tanhshrink = _un("tanhshrink", lambda x: x - jnp.tanh(x))
mish = _un("mish", lambda x: x * jnp.tanh(jax.nn.softplus(x)))


def relu_(x, name=None):
    out = relu(x)
    x._data, x._grad_node, x._output_index = out._data, out._grad_node, out._output_index
    return x


def tanh_(x, name=None):
    out = tanh(x)
    x._data, x._grad_node, x._output_index = out._data, out._grad_node, out._output_index
    return x


def elu(x, alpha=1.0, name=None):
    return D.apply("elu", lambda a, alpha: jax.nn.elu(a, alpha), (x,), {"alpha": float(alpha)})


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return D.apply("selu",
                   lambda a, scale, alpha: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)),
                   (x,), {"scale": float(scale), "alpha": float(alpha)})


def celu(x, alpha=1.0, name=None):
    return D.apply("celu", lambda a, alpha: jax.nn.celu(a, alpha), (x,), {"alpha": float(alpha)})


def gelu(x, approximate=False, name=None):
    return D.apply("gelu", lambda a, approx: jax.nn.gelu(a, approximate=approx),
                   (x,), {"approx": bool(approximate)})


def swish(x, name=None):
    return silu(x)


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return D.apply("hardsigmoid",
                   lambda a, slope, offset: jnp.clip(slope * a + offset, 0.0, 1.0),
                   (x,), {"slope": float(slope), "offset": float(offset)})


def hardswish(x, name=None):
    return D.apply("hardswish", lambda a: a * jnp.clip(a + 3.0, 0.0, 6.0) / 6.0, (x,))


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return D.apply("hardtanh", lambda a, mn, mx: jnp.clip(a, mn, mx),
                   (x,), {"mn": float(min), "mx": float(max)})


def hardshrink(x, threshold=0.5, name=None):
    return D.apply("hardshrink",
                   lambda a, t: jnp.where(jnp.abs(a) > t, a, jnp.zeros((), a.dtype)),
                   (x,), {"t": float(threshold)})


def softshrink(x, threshold=0.5, name=None):
    return D.apply("softshrink",
                   lambda a, t: jnp.where(a > t, a - t, jnp.where(a < -t, a + t, jnp.zeros((), a.dtype))),
                   (x,), {"t": float(threshold)})


def leaky_relu(x, negative_slope=0.01, name=None):
    return D.apply("leaky_relu",
                   lambda a, slope: jax.nn.leaky_relu(a, slope),
                   (x,), {"slope": float(negative_slope)})


def softmax(x, axis=-1, dtype=None, name=None):
    return D.apply("softmax", lambda a, axis: jax.nn.softmax(a, axis=axis),
                   (x,), {"axis": int(axis)})


def softmax_(x, axis=-1, dtype=None, name=None):
    out = softmax(x, axis, dtype)
    x._data, x._grad_node, x._output_index = out._data, out._grad_node, out._output_index
    return x


def log_softmax(x, axis=-1, dtype=None, name=None):
    return D.apply("log_softmax", lambda a, axis: jax.nn.log_softmax(a, axis=axis),
                   (x,), {"axis": int(axis)})


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return D.apply("softplus",
                   lambda a, beta, threshold: jnp.where(
                       beta * a > threshold, a, jax.nn.softplus(beta * a) / beta),
                   (x,), {"beta": float(beta), "threshold": float(threshold)})


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return D.apply("thresholded_relu",
                   lambda a, t, v: jnp.where(a > t, a, jnp.asarray(v, a.dtype)),
                   (x,), {"t": float(threshold), "v": float(value)})


def maxout(x, groups, axis=1, name=None):
    def _maxout(a, groups, axis):
        c = a.shape[axis]
        new_shape = list(a.shape)
        new_shape[axis] = c // groups
        new_shape.insert(axis + 1, groups)
        return jnp.max(a.reshape(new_shape), axis=axis + 1)
    return D.apply("maxout", _maxout, (x,), {"groups": int(groups), "axis": int(axis)})


def prelu(x, weight, data_format="NCHW", name=None):
    def _prelu(a, w, data_format):
        if w.size == 1:
            w_b = w.reshape(())
        else:
            shape = [1] * a.ndim
            ch_axis = 1 if data_format[1] == "C" else a.ndim - 1
            shape[ch_axis] = w.size
            w_b = w.reshape(shape)
        return jnp.where(a > 0, a, w_b * a)
    return D.apply("prelu", _prelu, (x, weight), {"data_format": data_format})


def rrelu(x, lower=0.125, upper=0.3333333, training=True, name=None):
    from ...core import random_state
    if training:
        key = random_state.next_key()
        return D.apply("rrelu_train",
                       lambda k, a, lo, hi: jnp.where(
                           a >= 0, a, a * jax.random.uniform(k, a.shape, a.dtype, lo, hi)),
                       (key, x), {"lo": float(lower), "hi": float(upper)})
    mid = (lower + upper) / 2.0
    return leaky_relu(x, mid)


def glu(x, axis=-1, name=None):
    def _glu(a, axis):
        return jax.nn.glu(a, axis=axis)
    return D.apply("glu", _glu, (x,), {"axis": int(axis)})


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...core import random_state
    key = random_state.next_key()

    def _gs(k, a, temperature, hard, axis):
        g = -jnp.log(-jnp.log(jax.random.uniform(k, a.shape, a.dtype, 1e-20, 1.0)))
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis)
            y_hard = jax.nn.one_hot(idx, a.shape[axis], axis=axis, dtype=a.dtype)
            y = y_hard + y - jax.lax.stop_gradient(y)  # straight-through
        return y
    return D.apply("gumbel_softmax", _gs, (key, x),
                   {"temperature": float(temperature), "hard": bool(hard), "axis": int(axis)})
