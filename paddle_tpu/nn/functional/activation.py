"""Activation functionals.

Parity with /root/reference/python/paddle/nn/functional/activation.py.

Most activations are kernel-driven schema ops (ops/ops.yaml `kernel:`
entries over ops/kernels.py; wrappers generated into
ops/generated/op_wrappers.py) and re-exported here.  What stays
hand-written: the inplace variants (tape-splice semantics) and the
random activations (rrelu, gumbel_softmax — they thread the framework
RNG stream as an extra input).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core import dispatch as D
from ...ops.generated.op_wrappers import (  # noqa: F401
    celu, elu, gelu, glu, hardshrink, hardsigmoid, hardswish, hardtanh,
    leaky_relu, log_sigmoid, log_softmax, maxout, mish, prelu, relu, relu6,
    selu, sigmoid, silu, softmax, softplus, softshrink, softsign, swish,
    tanh, tanhshrink, thresholded_relu,
)

__all__ = [
    "relu", "relu_", "relu6", "elu", "selu", "celu", "gelu", "silu", "swish",
    "sigmoid", "hardsigmoid", "hardswish", "hardtanh", "hardshrink",
    "softshrink", "tanhshrink", "leaky_relu", "log_sigmoid", "log_softmax",
    "softmax", "softmax_", "softplus", "softsign", "mish", "maxout", "prelu",
    "rrelu", "thresholded_relu", "glu", "gumbel_softmax", "tanh", "tanh_",
]


def relu_(x, name=None):
    out = relu(x)
    x._data, x._grad_node, x._output_index = out._data, out._grad_node, out._output_index
    return x


def tanh_(x, name=None):
    out = tanh(x)
    x._data, x._grad_node, x._output_index = out._data, out._grad_node, out._output_index
    return x


def softmax_(x, axis=-1, dtype=None, name=None):
    out = softmax(x, axis, dtype)
    x._data, x._grad_node, x._output_index = out._data, out._grad_node, out._output_index
    return x


def rrelu(x, lower=0.125, upper=0.3333333, training=True, name=None):
    from ...core import random_state
    if training:
        key = random_state.next_key()
        return D.apply("rrelu_train",
                       lambda k, a, lo, hi: jnp.where(
                           a >= 0, a, a * jax.random.uniform(k, a.shape, a.dtype, lo, hi)),
                       (key, x), {"lo": float(lower), "hi": float(upper)})
    mid = (lower + upper) / 2.0
    return leaky_relu(x, mid)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...core import random_state
    key = random_state.next_key()

    def _gs(k, a, temperature, hard, axis):
        g = -jnp.log(-jnp.log(jax.random.uniform(k, a.shape, a.dtype, 1e-20, 1.0)))
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis)
            y_hard = jax.nn.one_hot(idx, a.shape[axis], axis=axis, dtype=a.dtype)
            y = y_hard + y - jax.lax.stop_gradient(y)  # straight-through
        return y
    return D.apply("gumbel_softmax", _gs, (key, x),
                   {"temperature": float(temperature), "hard": bool(hard), "axis": int(axis)})
