"""Convolution functionals over lax.conv_general_dilated (MXU-native).

Parity with /root/reference/python/paddle/nn/functional/conv.py.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core import dispatch as D

__all__ = ["conv1d", "conv2d", "conv3d", "conv1d_transpose", "conv2d_transpose",
           "conv3d_transpose"]


def _tup(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(int(x) for x in v)


def _padding(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return tuple((padding, padding) for _ in range(n))
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return tuple((p, p) for p in padding)
    if len(padding) == 2 * n:
        return tuple((padding[2 * i], padding[2 * i + 1]) for i in range(n))
    # paddle also allows [[0,0],[0,0],[h0,h1],[w0,w1]]
    flat = [p for p in padding if not (isinstance(p, (list, tuple)) and tuple(p) == (0, 0))]
    return tuple(tuple(p) for p in flat)


def _conv(x, w, b, strides, padding, dilation, groups, nd, channels_last):
    if channels_last:
        lhs_spec = "N" + "DHW"[3 - nd:] + "C"
        out_spec = lhs_spec
    else:
        lhs_spec = "NC" + "DHW"[3 - nd:]
        out_spec = lhs_spec
    rhs_spec = "OI" + "DHW"[3 - nd:]
    dn = jax.lax.conv_dimension_numbers(x.shape, w.shape, (lhs_spec, rhs_spec, out_spec))
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=strides, padding=padding, rhs_dilation=dilation,
        dimension_numbers=dn, feature_group_count=groups,
        preferred_element_type=None)
    if b is not None:
        shape = [1] * out.ndim
        shape[out_spec.index("C")] = b.size
        out = out + b.reshape(shape)
    return out


def _conv_nd(name, nd):
    def op(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format=None, name=None):
        df = data_format or ("NCL" if nd == 1 else "NCHW" if nd == 2 else "NCDHW")
        channels_last = df.endswith("C")
        s = _tup(stride, nd)
        d = _tup(dilation, nd)
        p = _padding(padding, nd)
        args = (x, weight, bias) if bias is not None else (x, weight)
        static = {"strides": s, "padding": p, "dilation": d, "groups": int(groups),
                  "nd": nd, "channels_last": channels_last}
        if bias is not None:
            return D.apply(op_name, lambda a, w, b, **kw: _conv(a, w, b, **kw), args, static)
        return D.apply(op_name, lambda a, w, **kw: _conv(a, w, None, **kw), args, static)
    op_name = name
    op.__name__ = name
    return op


conv1d = _conv_nd("conv1d", 1)
conv2d = _conv_nd("conv2d", 2)
conv3d = _conv_nd("conv3d", 3)


def _conv_transpose(x, w, b, strides, padding, out_padding, dilation, groups, nd,
                    channels_last, output_size):
    if channels_last:
        lhs_spec = "N" + "DHW"[3 - nd:] + "C"
    else:
        lhs_spec = "NC" + "DHW"[3 - nd:]
    # paddle transpose-conv weight layout: [in_c, out_c/groups, *k]
    rhs_spec = "IO" + "DHW"[3 - nd:]
    dn = jax.lax.conv_dimension_numbers(x.shape, w.shape, (lhs_spec, rhs_spec, lhs_spec))
    if isinstance(padding, str):
        pad = padding
    else:
        # convert forward-conv padding semantics to transposed conv
        k_spatial = [w.shape[i] for i, ch in enumerate(rhs_spec) if ch in "DHW"]
        pad = tuple(
            (d_ * (k - 1) - p[0], d_ * (k - 1) - p[1] + op_)
            for k, p, d_, op_ in zip(k_spatial, padding, dilation, out_padding)
        )
    # transposed conv is the gradient of forward conv: correlation with the
    # SPATIALLY FLIPPED kernel (conv_general_dilated computes correlation,
    # so an asymmetric kernel needs the explicit flip)
    w = jnp.flip(w, axis=tuple(range(2, 2 + nd)))
    if groups > 1:
        # paddle weight [in_c, out_c/g, *k]; the IO-spec grouped call wants
        # rhs (in_c/g, out_c, *k) with the group blocks laid out along O
        in_c, out_per_g = w.shape[0], w.shape[1]
        spatial = w.shape[2:]
        w = w.reshape(groups, in_c // groups, out_per_g, *spatial)
        w = jnp.swapaxes(w, 0, 1).reshape(in_c // groups,
                                          groups * out_per_g, *spatial)
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=(1,) * nd, padding=pad, lhs_dilation=strides,
        rhs_dilation=dilation, dimension_numbers=dn, feature_group_count=groups)
    if b is not None:
        shape = [1] * out.ndim
        shape[lhs_spec.index("C")] = b.size
        out = out + b.reshape(shape)
    return out


def _conv_transpose_nd(name, nd):
    def op(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1,
           dilation=1, data_format=None, output_size=None, name=None):
        df = data_format or ("NCL" if nd == 1 else "NCHW" if nd == 2 else "NCDHW")
        channels_last = df.endswith("C")
        s = _tup(stride, nd)
        d = _tup(dilation, nd)
        op_pad = _tup(output_padding, nd)
        p = _padding(padding, nd)
        if isinstance(p, str):
            if p == "SAME":
                p = tuple((0, 0) for _ in range(nd))
            else:
                p = tuple((0, 0) for _ in range(nd))
        # flip weight group handling: paddle weight is [in, out/groups, *k]
        static = {"strides": s, "padding": p, "out_padding": op_pad, "dilation": d,
                  "groups": int(groups), "nd": nd, "channels_last": channels_last,
                  "output_size": None}
        args = (x, weight, bias) if bias is not None else (x, weight)
        if bias is not None:
            return D.apply(op_name, lambda a, w, b, **kw: _conv_transpose(a, w, b, **kw),
                           args, static)
        return D.apply(op_name, lambda a, w, **kw: _conv_transpose(a, w, None, **kw),
                       args, static)
    op_name = name
    op.__name__ = name
    return op


conv1d_transpose = _conv_transpose_nd("conv1d_transpose", 1)
conv2d_transpose = _conv_transpose_nd("conv2d_transpose", 2)
conv3d_transpose = _conv_transpose_nd("conv3d_transpose", 3)
