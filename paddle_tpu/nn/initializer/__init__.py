"""Parameter initializers.

Parity with /root/reference/python/paddle/nn/initializer/ (Constant, Normal,
TruncatedNormal, Uniform, XavierNormal/Uniform, KaimingNormal/Uniform,
Assign, Orthogonal, Dirac, calculate_gain).
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ...core import random_state
from ...core.dtype import convert_dtype
from .attr import ParamAttr

__all__ = [
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Orthogonal", "Dirac", "calculate_gain", "set_global_initializer",
    "ParamAttr",
]

_global_weight_init = None
_global_bias_init = None


def set_global_initializer(weight_init, bias_init=None):
    global _global_weight_init, _global_bias_init
    _global_weight_init, _global_bias_init = weight_init, bias_init


def _fans(shape):
    shape = tuple(shape)
    if len(shape) < 2:
        fan_in = fan_out = int(np.prod(shape)) if shape else 1
    else:
        receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
        # paddle convention for Linear: shape [in, out]
        fan_in, fan_out = shape[0] * receptive, shape[1] * receptive
        if len(shape) > 2:
            # conv kernels: [out_c, in_c, *k]
            fan_in, fan_out = shape[1] * receptive, shape[0] * receptive
    return fan_in, fan_out


def calculate_gain(nonlinearity, param=None):
    table = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "conv1d_transpose": 1.0, "conv2d_transpose": 1.0,
        "conv3d_transpose": 1.0, "tanh": 5.0 / 3, "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4,
    }
    if nonlinearity not in table:
        raise ValueError(f"unsupported nonlinearity: {nonlinearity}")
    return table[nonlinearity]


class Initializer:
    def __call__(self, shape, dtype="float32"):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        return jnp.full(tuple(shape), self.value, convert_dtype(dtype).np_dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype="float32"):
        k = random_state.next_key()
        dt = convert_dtype(dtype).np_dtype
        return self.mean + self.std * jax.random.normal(k, tuple(shape), dt)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0, name=None):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype="float32"):
        k = random_state.next_key()
        dt = convert_dtype(dtype).np_dtype
        lo = (self.a - self.mean) / self.std if self.std else self.a
        hi = (self.b - self.mean) / self.std if self.std else self.b
        return self.mean + self.std * jax.random.truncated_normal(k, lo, hi, tuple(shape), dt)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, name=None):
        self.low, self.high = low, high

    def __call__(self, shape, dtype="float32"):
        k = random_state.next_key()
        dt = convert_dtype(dtype).np_dtype
        return jax.random.uniform(k, tuple(shape), dt, self.low, self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype="float32"):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        k = random_state.next_key()
        return std * jax.random.normal(k, tuple(shape), convert_dtype(dtype).np_dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype="float32"):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        k = random_state.next_key()
        return jax.random.uniform(k, tuple(shape), convert_dtype(dtype).np_dtype,
                                  -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu", name=None):
        self.fan_in, self.slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype="float32"):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.slope)
        std = gain / math.sqrt(fi)
        k = random_state.next_key()
        return std * jax.random.normal(k, tuple(shape), convert_dtype(dtype).np_dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu", name=None):
        self.fan_in, self.slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype="float32"):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.slope)
        limit = gain * math.sqrt(3.0 / fi)
        k = random_state.next_key()
        return jax.random.uniform(k, tuple(shape), convert_dtype(dtype).np_dtype,
                                  -limit, limit)


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        from ...core.tensor import Tensor
        v = self.value
        if isinstance(v, Tensor):
            v = np.asarray(v._data)
        arr = jnp.asarray(np.asarray(v), convert_dtype(dtype).np_dtype)
        return arr.reshape(tuple(shape))


class Orthogonal(Initializer):
    def __init__(self, gain=1.0, name=None):
        self.gain = gain

    def __call__(self, shape, dtype="float32"):
        k = random_state.next_key()
        return self.gain * jax.nn.initializers.orthogonal()(
            k, tuple(shape), convert_dtype(dtype).np_dtype)


class Dirac(Initializer):
    def __init__(self, groups=1, name=None):
        self.groups = groups

    def __call__(self, shape, dtype="float32"):
        shape = tuple(shape)
        arr = np.zeros(shape, convert_dtype(dtype).np_dtype)
        out_c, in_c = shape[0], shape[1]
        mins = min(out_c // self.groups, in_c)
        centers = [s // 2 for s in shape[2:]]
        for g in range(self.groups):
            for i in range(mins):
                idx = (g * (out_c // self.groups) + i, i) + tuple(centers)
                arr[idx] = 1.0
        return jnp.asarray(arr)
