"""Weight-only quantization surface (reference python/paddle/nn/quant/
quantized_linear.py over the weight_quantize / weight_only_linear CUDA
kernels).

TPU-native formulation: quantization is pure jnp (absmax per-channel or
per-group int8/int4 with packed nibbles); weight_only_linear dequantizes
into the matmul's preferred dtype inside ONE dispatched program, so XLA
fuses dequant into the MXU matmul epilogue — the same "keep weights int8
in HBM, compute in bf16" economics as the reference's fast kernels.
llm.int8's outlier decomposition splits columns whose activation absmax
exceeds the threshold into a small fp matmul.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...core import dispatch as D
from ...core.tensor import Tensor

__all__ = ["weight_quantize", "weight_dequantize", "weight_only_linear",
           "llm_int8_linear"]

_ALGOS = ("weight_only_int8", "weight_only_int4", "llm.int8")


def _check(algo, group_size):
    if algo not in _ALGOS:
        raise ValueError(f"algo must be one of {_ALGOS}, got {algo!r}")
    if group_size not in (-1, 64, 128):
        raise ValueError(f"group_size must be -1/64/128, got {group_size}")


def _wq_impl(x, algo, group_size):
    # x [K, N] -> out int8 [N, K] (transposed, reference contract),
    # scale [N] f32 (per-channel) or [K/group, N] (grouped)
    xf = x.astype(jnp.float32)
    qmax = 7.0 if algo == "weight_only_int4" else 127.0
    if group_size == -1:
        scale = jnp.max(jnp.abs(xf), axis=0) / qmax          # [N]
        safe = jnp.where(scale == 0, 1.0, scale)             # all-zero chans
        q = jnp.round(xf / safe[None, :])
    else:
        K = xf.shape[0]
        g = xf.reshape(K // group_size, group_size, -1)
        scale = jnp.max(jnp.abs(g), axis=1) / qmax           # [K/gs, N]
        safe = jnp.where(scale == 0, 1.0, scale)
        q = jnp.round(g / safe[:, None, :]).reshape(xf.shape)
    q = jnp.clip(q, -qmax, qmax).astype(jnp.int8).T          # [N, K]
    if algo == "weight_only_int4":
        # pack two nibbles per byte along K -> [N, K//2]
        lo = q[:, 0::2].astype(jnp.int32) & 0xF
        hi = (q[:, 1::2].astype(jnp.int32) & 0xF) << 4
        q = (lo | hi).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def weight_quantize(x, algo="weight_only_int8", arch=None, group_size=-1):
    """Quantize a [K, N] fp weight; returns (int8 [N, K] — packed [N, K//2]
    for int4 — and per-channel/grouped scales)."""
    _check(algo, group_size)
    if algo == "weight_only_int4" and int(x.shape[0]) % 2:
        raise ValueError(
            f"weight_only_int4 packs two rows per byte; K={x.shape[0]} "
            "must be even")
    return D.apply("weight_quantize", _wq_impl, (x,),
                   {"algo": algo, "group_size": int(group_size)},
                   num_outputs=2)


def _unpack_int4(q):
    lo = (q.astype(jnp.int32) & 0xF)
    lo = jnp.where(lo >= 8, lo - 16, lo)                      # sign extend
    hi = (q.astype(jnp.int32) >> 4) & 0xF
    hi = jnp.where(hi >= 8, hi - 16, hi)
    out = jnp.stack([lo, hi], axis=-1).reshape(q.shape[0], -1)
    return out                                                # [N, K]


def _dequant(qw, scale, algo, group_size, dtype):
    q = _unpack_int4(qw) if algo == "weight_only_int4" \
        else qw.astype(jnp.int32)                             # [N, K]
    qf = q.astype(jnp.float32).T                              # [K, N]
    if scale.ndim == 1:
        w = qf * scale[None, :]
    else:                                                     # [K/gs, N]
        K = qf.shape[0]
        gs = K // scale.shape[0]
        w = (qf.reshape(-1, gs, qf.shape[1])
             * scale[:, None, :]).reshape(qf.shape)
    return w.astype(dtype)


def _wdq_impl(qw, scale, algo, group_size, out_dtype):
    return _dequant(qw, scale, algo, group_size, jnp.dtype(out_dtype))


def weight_dequantize(x, scale, algo="weight_only_int8", out_dtype="float16",
                      group_size=-1):
    """Inverse of weight_quantize: int8/int4-packed [N, K] -> fp [K, N]."""
    _check(algo, group_size)
    return D.apply("weight_dequantize", _wdq_impl, (x, scale),
                   {"algo": algo, "group_size": int(group_size),
                    "out_dtype": str(out_dtype)})


def _wol_impl(x, qw, scale, *maybe_bias, algo, group_size, has_bias):
    w = _dequant(qw, scale, algo, group_size, x.dtype)        # [K, N]
    y = jnp.matmul(x, w)
    if has_bias:
        y = y + maybe_bias[0].astype(y.dtype)
    return y


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", arch=None, group_size=-1):
    """y = x @ dequant(weight)^T' + bias with int8/int4 weights kept
    quantized in HBM; dequant fuses into the matmul program."""
    algo = "weight_only_int4" if str(weight_dtype) == "int4" \
        else "weight_only_int8"
    _check(algo, group_size)
    if weight_scale is None:
        raise ValueError("weight_only_linear requires weight_scale")
    args = (x, weight, weight_scale) + ((bias,) if bias is not None else ())
    return D.apply("weight_only_linear", _wol_impl, args,
                   {"algo": algo, "group_size": int(group_size),
                    "has_bias": bias is not None})


def _llm_int8_impl(x, qw, scale, *maybe_bias, threshold, has_bias):
    # outlier decomposition (LLM.int8()): activation columns whose absmax
    # exceeds threshold run against the fp weight; the rest stay int8
    w = _dequant(qw, scale, "weight_only_int8", -1, jnp.float32)  # [K, N]
    xf = x.astype(jnp.float32)
    col_amax = jnp.max(jnp.abs(xf), axis=tuple(range(xf.ndim - 1)))
    outlier = col_amax > threshold                            # [K]
    x_in = jnp.where(outlier[None, :], 0.0, xf.reshape(-1, xf.shape[-1]))
    x_out = jnp.where(outlier[None, :], xf.reshape(-1, xf.shape[-1]), 0.0)
    # inlier path: requantize activations to int8 per-row (absmax)
    row_s = jnp.max(jnp.abs(x_in), axis=1, keepdims=True) / 127.0
    row_s = jnp.where(row_s == 0, 1.0, row_s)
    xq = jnp.round(x_in / row_s).astype(jnp.int8)
    y_in = (jnp.matmul(xq.astype(jnp.int32),
                       jnp.round(w / jnp.where(
                           jnp.max(jnp.abs(w), 0, keepdims=True) == 0, 1.0,
                           jnp.max(jnp.abs(w), 0, keepdims=True) / 127.0)
                       ).astype(jnp.int32))
            .astype(jnp.float32)
            * row_s * (jnp.max(jnp.abs(w), 0) / 127.0)[None, :])
    y = y_in + jnp.matmul(x_out, w)
    if has_bias:
        y = y + maybe_bias[0].astype(jnp.float32)
    return y.reshape(x.shape[:-1] + (w.shape[1],)).astype(x.dtype)


def llm_int8_linear(x, weight, bias=None, weight_scale=None,
                    threshold=6.0):
    """LLM.int8() linear: int8 matmul for inlier activation columns,
    fp path for outlier columns above `threshold` (reference
    llm_int8_linear over the cuBLAS int8 kernels)."""
    if weight_scale is None:
        raise ValueError("llm_int8_linear requires weight_scale")
    args = (x, weight, weight_scale) + ((bias,) if bias is not None else ())
    return D.apply("llm_int8_linear", _llm_int8_impl, args,
                   {"threshold": float(threshold),
                    "has_bias": bias is not None})
