"""Gradient clipping.

Parity with /root/reference/python/paddle/nn/clip.py (ClipGradByValue,
ClipGradByNorm, ClipGradByGlobalNorm) — operates on (param, grad) lists the
way the reference optimizers consume them.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm",
           "clip_grad_norm_", "clip_grad_value_"]


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._data, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g._data.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, Tensor((g._data * scale).astype(g._data.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        sq = []
        for p, g in params_grads:
            if g is None:
                continue
            if getattr(p, "need_clip", True):
                sq.append(jnp.sum(jnp.square(g._data.astype(jnp.float32))))
        if not sq:
            return params_grads
        global_norm = jnp.sqrt(sum(sq))
        scale = jnp.minimum(self.clip_norm / jnp.maximum(global_norm, 1e-12), 1.0)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
            else:
                out.append((p, Tensor((g._data.astype(jnp.float32) * scale)
                                      .astype(g._data.dtype))))
        return out


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p._grad for p in parameters if p._grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g._data)) for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g._data.astype(jnp.float32)) ** norm_type) for g in grads]
        )) ** (1.0 / norm_type)
    scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-6), 1.0)
    for g in grads:
        g._data = (g._data * scale).astype(g._data.dtype)
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p._grad is not None:
            p._grad._data = jnp.clip(p._grad._data, -clip_value, clip_value)
