"""Live-buffer accounting — the allocator-facade view.

The reference's StatAllocator/allocator facade
(/root/reference/paddle/phi/core/memory/stats.cc + allocation/allocator_
facade.cc) tracks every allocation so tooling can enumerate what is
resident.  Under PJRT the runtime owns allocation, but XLA's client keeps
the exact live set — ``jax.live_arrays()`` — so live-buffer accounting
here is an exact enumeration with zero per-op bookkeeping overhead, plus
the native peak gauges (csrc/stats.cc) for cross-checks.
"""
from __future__ import annotations

__all__ = ["live_buffers", "live_buffer_bytes", "memory_summary",
           "live_tensor_count"]


def _arrays(device=None):
    import jax

    arrays = jax.live_arrays()
    if device is not None:
        dev = device if not isinstance(device, str) else None
        if dev is None:  # "tpu:0"-style string
            plat, _, idx = str(device).partition(":")
            idx = int(idx or 0)
            dev = jax.devices(plat)[idx]
        arrays = [a for a in arrays
                  if dev in getattr(a, "devices", lambda: set())()]
    return arrays


def live_buffers(device=None):
    """[(shape, dtype, nbytes)] for every live device array, largest
    first — the reference allocator facade's live-allocation listing."""
    out = []
    for a in _arrays(device):
        try:
            out.append((tuple(a.shape), str(a.dtype), int(a.nbytes)))
        except Exception:
            continue
    out.sort(key=lambda t: -t[2])
    return out


def live_buffer_bytes(device=None) -> int:
    return sum(b for _, _, b in live_buffers(device))


def live_tensor_count() -> int:
    """Framework Tensors currently alive (leak triage: a rising count with
    flat live_buffer_bytes means Tensor wrappers are retained, not data)."""
    import gc

    from ..core.tensor import Tensor
    return sum(1 for o in gc.get_objects() if isinstance(o, Tensor))


def memory_summary(device=None) -> str:
    """Human-readable allocator view (reference memory_summary analog):
    totals, per-dtype aggregation, top allocations, runtime stats."""
    from collections import defaultdict

    bufs = live_buffers(device)
    total = sum(b for _, _, b in bufs)
    by_dtype = defaultdict(lambda: [0, 0])
    for _, dt, b in bufs:
        by_dtype[dt][0] += 1
        by_dtype[dt][1] += b
    lines = [
        "=== paddle_tpu memory summary ===",
        f"live buffers : {len(bufs)}",
        f"live bytes   : {total:,} ({total / 2**20:.1f} MiB)",
        "-- by dtype --",
    ]
    for dt, (n, b) in sorted(by_dtype.items(), key=lambda kv: -kv[1][1]):
        lines.append(f"  {dt:<10} x{n:<6} {b / 2**20:>10.1f} MiB")
    lines.append("-- largest buffers --")
    for shape, dt, b in bufs[:10]:
        lines.append(f"  {str(shape):<24} {dt:<10} {b / 2**20:>10.1f} MiB")
    try:
        import jax
        stats = jax.devices()[0].memory_stats() or {}
        if stats:
            lines.append("-- device runtime stats --")
            for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
                if k in stats:
                    lines.append(f"  {k:<18} {stats[k]:,}")
    except Exception:
        pass
    return "\n".join(lines)
