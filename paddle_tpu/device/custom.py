"""Custom-device plugin ABI.

Reference counterpart: the pluggable-device interface
(/root/reference/paddle/phi/backends/device_base.h:26 DeviceInterface —
Init/SetDevice/stream/event/memcpy/alloc virtuals, registered through
DeviceManager, device_manager.h:134; vendors ship a dlopen'd plugin, and the
test suite exercises the ABI with a fake device,
paddle/phi/backends/custom/fake_cpu_device.h + test/custom_runtime/).

TPU-native split of that ABI:
- The COMPUTE plug-in point on an XLA stack is a PJRT plugin: jax discovers
  `jax_plugins` entry points and `register_plugin` at import; a vendor
  backend arrives as a pip package, not a paddle-specific .so.
  ``register_pjrt_plugin`` wraps that registration.
- What remains framework-owned — the registry, device naming
  (``custom_dev:0``), host-callback devices for prototyping — is this
  module: ``CustomDeviceInterface`` mirrors DeviceInterface's virtuals at
  python level, and registered types surface through
  ``paddle.device.get_all_custom_device_type()`` exactly like the
  reference's runtime query.
"""
from __future__ import annotations

__all__ = ["CustomDeviceInterface", "register_custom_device",
           "unregister_custom_device", "registered_custom_devices",
           "get_custom_device", "register_pjrt_plugin", "FakeCPUDevice"]

_REGISTRY: dict = {}


class CustomDeviceInterface:
    """Python mirror of the reference DeviceInterface virtual table
    (device_base.h:26).  Subclass and override; defaults are sane no-ops so
    a minimal host device only needs `memory_copy`/`allocate`."""

    #: device type name, e.g. "fake_cpu" (reference GetDeviceType)
    device_type: str = "custom"

    def init(self):                                    # Init()
        return None

    def visible_device_count(self) -> int:             # GetDeviceCount()
        return 1

    def set_device(self, dev_id: int):                 # SetDevice()
        return None

    def allocate(self, size: int):                     # MemoryAllocate()
        return bytearray(size)

    def deallocate(self, ptr):                         # MemoryDeallocate()
        return None

    def memory_copy(self, dst, src, size: int,         # MemoryCopyH2D/D2H
                    kind: str = "h2d"):
        dst[:size] = src[:size]

    def create_stream(self):                           # CreateStream()
        return object()

    def synchronize(self, dev_id: int = 0):            # SynchronizeDevice()
        return None

    def get_memory_stats(self, dev_id: int = 0):       # MemoryStats()
        return {"total": 0, "free": 0}


def register_custom_device(impl: CustomDeviceInterface):
    """Register a device plugin (reference DeviceManager::Register via
    phi/capi; also LoadCustomRuntimeLib for .so plugins)."""
    if not isinstance(impl, CustomDeviceInterface):
        raise TypeError("impl must be a CustomDeviceInterface")
    name = impl.device_type
    if name in _REGISTRY:
        raise ValueError(f"custom device {name!r} already registered")
    impl.init()
    _REGISTRY[name] = impl
    return impl


def unregister_custom_device(device_type: str):
    _REGISTRY.pop(device_type, None)


def registered_custom_devices() -> list:
    return sorted(_REGISTRY)


def get_custom_device(device_type: str) -> CustomDeviceInterface:
    try:
        return _REGISTRY[device_type]
    except KeyError:
        raise ValueError(
            f"no custom device {device_type!r}; registered: "
            f"{sorted(_REGISTRY)}") from None


def register_pjrt_plugin(name: str, library_path: str, options=None):
    """Register a PJRT plugin as a JAX backend — the XLA-stack equivalent of
    the reference's dlopen'd custom-runtime .so.  After registration the
    device is a first-class jax backend (visible to jax.devices(name))."""
    from jax._src.xla_bridge import register_plugin
    register_plugin(name, library_path=library_path, options=options)


class FakeCPUDevice(CustomDeviceInterface):
    """Host-memory fake device (reference fake_cpu_device.h — used by
    test/custom_runtime/ to exercise the ABI without hardware)."""

    device_type = "fake_cpu"

    def __init__(self, count: int = 2):
        self._count = count
        self._streams = 0
        self._current = 0
        self.initialized = False

    def init(self):
        self.initialized = True

    def visible_device_count(self):
        return self._count

    def set_device(self, dev_id):
        if not 0 <= dev_id < self._count:
            raise ValueError(f"fake_cpu has {self._count} devices")
        self._current = dev_id

    def create_stream(self):
        self._streams += 1
        return self._streams

    def get_memory_stats(self, dev_id=0):
        return {"total": 1 << 30, "free": 1 << 29}
