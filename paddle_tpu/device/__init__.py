"""device namespace.

Parity with /root/reference/python/paddle/device/ — set_device/get_device,
synchronization, stream no-ops (XLA owns scheduling on TPU), and a cuda
compatibility shim mapping onto the accelerator.
"""
from __future__ import annotations

import jax

from ..core.place import (  # noqa: F401
    CPUPlace, CUDAPlace, Place, TPUPlace, XPUPlace, device_count, get_device,
    get_all_device_type, set_device,
)

__all__ = ["set_device", "get_device", "get_all_device_type",
           "get_all_custom_device_type", "get_available_device",
           "get_available_custom_device", "synchronize", "device_count",
           "Stream", "Event", "current_stream", "set_stream", "stream_guard",
           "get_cudnn_version", "is_compiled_with_cinn", "IS_WINDOWS", "cuda",
           "custom", "memory", "live_buffers", "live_buffer_bytes",
           "memory_summary"]

from . import custom  # noqa: E402,F401
from . import memory  # noqa: E402,F401
from .memory import (  # noqa: E402,F401
    live_buffer_bytes, live_buffers, memory_summary)

IS_WINDOWS = False


def get_all_custom_device_type():
    from .custom import registered_custom_devices
    return registered_custom_devices()


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    from .custom import get_custom_device, registered_custom_devices
    out = []
    for t in registered_custom_devices():
        n = get_custom_device(t).visible_device_count()
        out.extend(f"{t}:{i}" for i in range(n))
    return out


def synchronize(device=None):
    """Block until all queued device work completes (paddle.device.synchronize)."""
    try:
        arr = jax.numpy.zeros(())
        arr.block_until_ready()
    except Exception:
        pass


def get_cudnn_version():
    return None


def is_compiled_with_cinn():
    return False


class Event:
    """Stream event shim: XLA's async dispatch orders work for us; record/query
    map onto array readiness."""

    def __init__(self, device=None, enable_timing=False, blocking=False,
                 interprocess=False):
        self._marker = None

    def record(self, stream=None):
        import jax.numpy as jnp
        self._marker = jnp.zeros(())

    def query(self):
        return True

    def synchronize(self):
        if self._marker is not None:
            self._marker.block_until_ready()


class Stream:
    """Stream shim: TPU execution order is managed by XLA; kept for API parity."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        event.synchronize()

    def wait_stream(self, stream):
        stream.synchronize()

    def record_event(self, event=None):
        event = event or Event()
        event.record(self)
        return event

    def query(self):
        return True


_current_stream = Stream()


def current_stream(device=None):
    return _current_stream


def set_stream(stream):
    global _current_stream
    prev = _current_stream
    _current_stream = stream
    return prev


class stream_guard:
    def __init__(self, stream):
        self.stream = stream

    def __enter__(self):
        self._prev = set_stream(self.stream)

    def __exit__(self, *exc):
        set_stream(self._prev)


class _CudaShim:
    """paddle.device.cuda API mapped onto the TPU runtime."""

    Stream = Stream
    Event = Event

    @staticmethod
    def device_count():
        return device_count()

    @staticmethod
    def synchronize(device=None):
        synchronize()

    @staticmethod
    def current_stream(device=None):
        return current_stream()

    @staticmethod
    def stream_guard(stream):
        return stream_guard(stream)

    @staticmethod
    def max_memory_allocated(device=None):
        try:
            stats = jax.devices()[0].memory_stats()
            return stats.get("peak_bytes_in_use", 0)
        except Exception:
            return 0

    @staticmethod
    def max_memory_reserved(device=None):
        return _CudaShim.max_memory_allocated(device)

    @staticmethod
    def memory_allocated(device=None):
        try:
            stats = jax.devices()[0].memory_stats()
            return stats.get("bytes_in_use", 0)
        except Exception:
            return 0

    @staticmethod
    def memory_reserved(device=None):
        return _CudaShim.memory_allocated(device)

    @staticmethod
    def empty_cache():
        return None

    @staticmethod
    def get_device_properties(device=None):
        d = jax.devices()[0]
        class _Props:
            name = getattr(d, "device_kind", "TPU")
            total_memory = 0
        return _Props()


cuda = _CudaShim()
