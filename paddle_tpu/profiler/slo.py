"""SLO observatory: windowed telemetry, burn-rate state, anomaly capture.

``ServingStats`` (profiler/serving.py) keeps LIFETIME aggregates — exact
counters plus bounded reservoirs — which answer "how did this run do"
but not "how is the service doing RIGHT NOW".  This module adds the
windowed side of the story, attached to a ``ServingStats`` via
``enable_windows()`` and surfaced through ``snapshot()``, ``/metrics``
and the frontend's ``GET /slo`` endpoint:

* **Ring-of-buckets rolling windows.**  Each latency channel (TTFT,
  ITL, step duration, queue wait, request latency) holds one ``_Ring``
  per window length (10s/60s/300s by default): a fixed array of time
  buckets, each a fixed-bound histogram on the same ladder as
  ``_HIST_BOUNDS``, rotated in place by ``time.perf_counter`` (never
  wall clock — see the ``wallclock-in-timing-path`` lint rule).  A
  bucket is reused when its generation stamp goes stale, so memory is
  O(windows x buckets x bounds) forever and a reader always sees the
  trailing window to one-bucket granularity.  Because every replica
  shares the ladder, fleet aggregation SUMS bucket counts index-by-
  index (``aggregate_windows``) and recomputes honest fleet
  percentiles — no max-of-quantiles bound.
* **Declarative SLOs with multi-window burn rates.**  ``SLOConfig``
  names the objectives (ttft_p95_ms, itl_p99_ms, deadline_attainment,
  availability); ``evaluate_slo`` turns each window into a BURN RATE —
  observed error fraction over the error budget the objective leaves
  (the SRE convention: burn 1.0 consumes exactly the budget, 2.0
  consumes it twice as fast) — and ``SLOMonitor`` folds the windows
  into one state: PAGE when the short AND medium windows both burn
  past ``page_burn`` (sustained, fast burn), WARN when the medium or
  long window burns past ``warn_burn``, NORMAL otherwise.  Transitions
  land as tracer instants (``slo.transition``) and in a bounded deque.
* **Anomaly-triggered capture.**  ``AnomalyDetector`` flags outliers
  with a robust median + k*MAD threshold over a bounded rolling sample
  (immune to the outliers it hunts, unlike mean/stddev); when armed
  with a Tracer ring and a flight recorder, ``WindowedTelemetry``
  snapshots the trace window plus the offending flight records into an
  ``AnomalySpool`` — a bounded on-disk directory that counts what it
  drops instead of growing without bound.

Everything here is opt-in and bounded: a ``ServingStats`` that never
called ``enable_windows()`` never executes a line of this file (pinned
by tracemalloc test), and every buffer is a ring, a reservoir, or a
capped deque (see the ``unbounded-observability-buffer`` lint rule).
"""
from __future__ import annotations

import bisect
import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass

from ..analysis.lock_check import install as _install_lock_check

__all__ = ["SLOConfig", "SLOMonitor", "WindowedTelemetry",
           "AnomalyDetector", "AnomalySpool", "evaluate_slo",
           "aggregate_windows", "SLO_STATE_NAMES",
           "NORMAL", "WARN", "PAGE"]

# shared with profiler/serving.py's _Hist: identical ladders are what
# make bucket counts summable across replicas
_BOUNDS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
           0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

_WINDOWS = (10.0, 60.0, 300.0)

NORMAL, WARN, PAGE = 0, 1, 2
SLO_STATE_NAMES = {NORMAL: "NORMAL", WARN: "WARN", PAGE: "PAGE"}

_LATENCY_CHANNELS = ("ttft", "itl", "step", "queue_wait", "request")
_RATE_CHANNELS = ("accept", "deadline", "availability")


def _wlabel(seconds: float) -> str:
    return f"{seconds:g}s"


def bucket_percentile(counts, q: float, bounds=_BOUNDS) -> float:
    """Percentile (seconds) from non-cumulative bucket counts on the
    shared ladder, with Prometheus-style linear interpolation inside
    the bucket; the +Inf bucket clamps to the highest finite bound."""
    total = sum(counts)
    if total <= 0:
        return 0.0
    target = q / 100.0 * total
    cum = 0.0
    lo = 0.0
    for i, c in enumerate(counts):
        if c and cum + c >= target:
            if i >= len(bounds):
                return bounds[-1]
            hi = bounds[i]
            return lo + (target - cum) / c * (hi - lo)
        cum += c
        if i < len(bounds):
            lo = bounds[i]
    return bounds[-1]


def _frac_over(counts, threshold_s: float, bounds=_BOUNDS) -> float:
    """Fraction of samples above ``threshold_s``, bucket-approximated:
    a sample is "good" when its whole bucket sits at or under the
    threshold (conservative for thresholds between bucket edges)."""
    total = sum(counts)
    if total <= 0:
        return 0.0
    good = sum(c for i, c in enumerate(counts)
               if i < len(bounds) and bounds[i] <= threshold_s)
    return (total - good) / total


@_install_lock_check
class _Ring:
    """One rolling window over one latency channel: a fixed ring of
    time buckets, each a fixed-bound histogram.  ``n_buckets`` bounds
    the memory; generation stamps recycle stale buckets in place, so
    the ring never allocates after construction."""

    __slots__ = ("window_s", "span", "n_buckets", "_counts", "_sums",
                 "_ns", "_gen", "_lock")

    def __init__(self, window_s: float, n_buckets: int = 12):
        self.window_s = float(window_s)
        self.n_buckets = int(n_buckets)
        self.span = self.window_s / self.n_buckets
        nb = len(_BOUNDS) + 1
        self._counts = [[0] * nb for _ in range(self.n_buckets)]
        self._sums = [0.0] * self.n_buckets
        self._ns = [0] * self.n_buckets
        self._gen = [-1] * self.n_buckets     # absolute bucket index
        self._lock = threading.Lock()

    def _slot(self, now: float) -> int:  # guarded-by: _lock
        g = int(now / self.span)
        i = g % self.n_buckets
        if self._gen[i] != g:
            self._gen[i] = g
            c = self._counts[i]
            for j in range(len(c)):
                c[j] = 0
            self._sums[i] = 0.0
            self._ns[i] = 0
        return i

    def add(self, now: float, v: float, n: int = 1) -> None:
        b = bisect.bisect_left(_BOUNDS, v)
        with self._lock:
            i = self._slot(now)
            self._counts[i][b] += n
            self._sums[i] += v * n
            self._ns[i] += n

    def merged(self, now: float):
        """(counts, sum, count) over the buckets still inside the
        window at ``now`` — the read surface snapshots render."""
        g_now = int(now / self.span)
        out = [0] * (len(_BOUNDS) + 1)
        total = 0.0
        n = 0
        with self._lock:
            for i in range(self.n_buckets):
                g = self._gen[i]
                if g < 0 or g_now - g >= self.n_buckets:
                    continue
                c = self._counts[i]
                for j, cj in enumerate(c):
                    out[j] += cj
                total += self._sums[i]
                n += self._ns[i]
        return out, total, n


class _RateRing:
    """Rolling numerator/denominator window (accept rate, deadline
    attainment, availability) on the same generation-stamped ring as
    ``_Ring`` — bounded to n_buckets pairs forever."""

    __slots__ = ("window_s", "span", "n_buckets", "_num", "_den",
                 "_gen", "_lock")

    def __init__(self, window_s: float, n_buckets: int = 12):
        self.window_s = float(window_s)
        self.n_buckets = int(n_buckets)
        self.span = self.window_s / self.n_buckets
        self._num = [0] * self.n_buckets
        self._den = [0] * self.n_buckets
        self._gen = [-1] * self.n_buckets
        self._lock = threading.Lock()

    def add(self, now: float, num: int, den: int) -> None:
        g = int(now / self.span)
        i = g % self.n_buckets
        with self._lock:
            if self._gen[i] != g:
                self._gen[i] = g
                self._num[i] = 0
                self._den[i] = 0
            self._num[i] += num
            self._den[i] += den

    def merged(self, now: float):
        g_now = int(now / self.span)
        num = den = 0
        with self._lock:
            for i in range(self.n_buckets):
                g = self._gen[i]
                if g < 0 or g_now - g >= self.n_buckets:
                    continue
                num += self._num[i]
                den += self._den[i]
        return num, den


@dataclass(frozen=True)
class SLOConfig:
    """Declarative service-level objectives.

    ``ttft_p95_ms``/``itl_p99_ms`` are latency thresholds: the
    objective is "at most 5% (resp. 1%) of samples above the
    threshold", so the error budget is that tail fraction.
    ``deadline_attainment``/``availability`` are success-fraction
    floors over finished requests.  ``warn_burn``/``page_burn`` are
    the burn-rate trip points for the WARN and PAGE states."""

    ttft_p95_ms: float = 500.0
    itl_p99_ms: float = 200.0
    deadline_attainment: float = 0.99
    availability: float = 0.999
    warn_burn: float = 1.0
    page_burn: float = 2.0

    def to_dict(self) -> dict:
        return {"ttft_p95_ms": self.ttft_p95_ms,
                "itl_p99_ms": self.itl_p99_ms,
                "deadline_attainment": self.deadline_attainment,
                "availability": self.availability,
                "warn_burn": self.warn_burn,
                "page_burn": self.page_burn}


def evaluate_slo(config, windows: dict) -> dict:
    """Stateless SLO evaluation of one ``windows`` snapshot (the dict
    ``WindowedTelemetry.snapshot()`` builds, or the fleet-pooled one
    from ``aggregate_windows``).  Returns burn rates per objective per
    window plus the folded state — shared by the live ``SLOMonitor``
    and the fleet aggregation path so one replica and a router agree
    on semantics."""
    if not isinstance(config, SLOConfig):
        config = SLOConfig(**{k: v for k, v in dict(config).items()
                              if k in SLOConfig.__dataclass_fields__})
    labels = [k for k in windows if k != "bounds"]
    labels.sort(key=lambda s: float(s[:-1]))
    burn: dict = {}
    for label in labels:
        w = windows[label]
        b: dict = {}
        b["ttft"] = _frac_over(w["ttft"]["buckets"],
                               config.ttft_p95_ms / 1e3) / 0.05
        b["itl"] = _frac_over(w["itl"]["buckets"],
                              config.itl_p99_ms / 1e3) / 0.01
        d = w["deadline"]
        if d["den"]:
            budget = max(1e-9, 1.0 - config.deadline_attainment)
            b["deadline"] = (1.0 - d["num"] / d["den"]) / budget
        a = w["availability"]
        if a["den"]:
            budget = max(1e-9, 1.0 - config.availability)
            b["availability"] = (1.0 - a["num"] / a["den"]) / budget
        b["max"] = max(b.values()) if b else 0.0
        burn[label] = {k: round(v, 4) for k, v in b.items()}
    state = NORMAL
    if labels:
        short = burn[labels[0]]["max"]
        mid = burn[labels[min(1, len(labels) - 1)]]["max"]
        long_ = burn[labels[-1]]["max"]
        if short >= config.page_burn and mid >= config.page_burn:
            state = PAGE
        elif mid >= config.warn_burn or long_ >= config.warn_burn:
            state = WARN
    return {"state": state, "state_name": SLO_STATE_NAMES[state],
            "burn_rates": burn, "config": config.to_dict()}


class SLOMonitor:
    """Stateful wrapper over ``evaluate_slo``: remembers the current
    state, records every transition into a bounded deque, and emits a
    ``slo.transition`` tracer instant when a tracer is armed."""

    TRANSITIONS = 64   # bounded transition history (deque maxlen)

    def __init__(self, config: SLOConfig | None = None, *,
                 tracer=None, track: str | None = None):
        self.config = config or SLOConfig()
        self.state = NORMAL
        self.transitions: deque = deque(maxlen=self.TRANSITIONS)
        self._tracer = tracer
        self._track = track

    def arm_tracer(self, tracer, track: str | None = None) -> None:
        self._tracer = tracer
        self._track = track

    def evaluate(self, windows: dict) -> dict:
        out = evaluate_slo(self.config, windows)
        new = out["state"]
        if new != self.state:
            self.transitions.append((self.state, new))
            tr = self._tracer
            if tr is not None:
                tr.instant("slo.transition", track=self._track,
                           args={"from": SLO_STATE_NAMES[self.state],
                                 "to": SLO_STATE_NAMES[new]})
            self.state = new
        out["transitions"] = len(self.transitions)
        return out


class AnomalyDetector:
    """Robust outlier detector over a rolling sample: a value is
    anomalous when it exceeds median + k*MAD of the recent window
    (median absolute deviation — the estimator outliers cannot drag,
    unlike mean/stddev).  The sample deque is bounded (maxlen), a
    minimum sample count gates cold starts, an absolute floor keeps a
    near-constant stream (MAD ~ 0) from flagging noise, and a cooldown
    bounds the capture rate under sustained misbehaviour."""

    def __init__(self, *, window: int = 256, k: float = 8.0,
                 min_samples: int = 24, floor_s: float = 1e-4,
                 cooldown_s: float = 2.0,
                 clock=time.perf_counter):
        self._recent: deque = deque(maxlen=int(window))
        self.k = float(k)
        self.min_samples = int(min_samples)
        self.floor_s = float(floor_s)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._last_fire = -1e18
        self.detected = 0          # anomalies seen (incl. cooldown-muted)
        self.last: dict = {}       # forensics of the latest detection

    def observe(self, v: float) -> bool:
        """Feed one value; True when it is an actionable anomaly (past
        threshold AND outside the cooldown)."""
        v = float(v)
        rec = self._recent
        fire = False
        if len(rec) >= self.min_samples:
            s = sorted(rec)
            n = len(s)
            med = s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])
            dev = sorted(abs(x - med) for x in s)
            mad = dev[n // 2] if n % 2 else 0.5 * (dev[n // 2 - 1]
                                                   + dev[n // 2])
            thresh = med + self.k * max(mad, self.floor_s)
            if v > thresh:
                self.detected += 1
                self.last = {"value_s": v, "median_s": med, "mad_s": mad,
                             "threshold_s": thresh}
                now = self._clock()
                if now - self._last_fire >= self.cooldown_s:
                    self._last_fire = now
                    fire = True
        rec.append(v)
        return fire


class AnomalySpool:
    """Bounded on-disk spool of anomaly snapshots.  At most
    ``max_files`` JSON files ever live under ``path``; captures past
    the bound are DROPPED and counted (``dropped``) — the spool tells
    you how much it shed rather than eating the disk."""

    def __init__(self, path, *, max_files: int = 32):
        self.path = os.fspath(path)
        self.max_files = int(max_files)
        os.makedirs(self.path, exist_ok=True)
        self._lock = threading.Lock()
        self._seq = len([f for f in os.listdir(self.path)
                         if f.startswith("anomaly-")])
        self.captured = 0
        self.dropped = 0

    def capture(self, payload: dict) -> str | None:
        """Write one snapshot; returns its path, or None (counted in
        ``dropped``) when the spool is full."""
        with self._lock:
            if self._seq >= self.max_files:
                self.dropped += 1
                return None
            seq = self._seq
            self._seq += 1
        fname = os.path.join(self.path, f"anomaly-{seq:06d}.json")
        with open(fname, "w", encoding="utf-8") as f:
            json.dump(payload, f)
        with self._lock:
            self.captured += 1
        return fname


class WindowedTelemetry:
    """The windowed surface a ``ServingStats`` grows when
    ``enable_windows()`` is called: one ring per (channel, window),
    the SLO monitor, and (when armed) the anomaly capture pipeline.
    Recording is a bisect plus a few list writes under one small lock
    per ring; nothing here allocates per event after construction
    except an anomaly capture itself."""

    def __init__(self, slo: SLOConfig | None = None, *,
                 windows=_WINDOWS, n_buckets: int = 12,
                 tracer=None, track: str | None = None,
                 clock=time.perf_counter):
        self.windows = tuple(float(w) for w in windows)
        self._clock = clock
        self._lat = {ch: {_wlabel(w): _Ring(w, n_buckets)
                          for w in self.windows}
                     for ch in _LATENCY_CHANNELS}
        self._rate = {ch: {_wlabel(w): _RateRing(w, n_buckets)
                           for w in self.windows}
                      for ch in _RATE_CHANNELS}
        self.slo = SLOMonitor(slo, tracer=tracer, track=track)
        # anomaly capture (armed separately; all refs optional)
        self._step_detector: AnomalyDetector | None = None
        self._request_detector: AnomalyDetector | None = None
        self.spool: AnomalySpool | None = None
        self._tracer = tracer
        self._flight = None

    # -- arming -------------------------------------------------------------

    def arm_tracer(self, tracer, track: str | None = None) -> None:
        """Route SLO transitions (and anomaly trace capture) through
        ``tracer`` — typically the small always-on ring the frontend
        keeps when an anomaly spool is configured."""
        self._tracer = tracer
        self.slo.arm_tracer(tracer, track)

    def arm_anomaly(self, *, spool: AnomalySpool | None = None,
                    tracer=None, flight=None,
                    step_detector: AnomalyDetector | None = None,
                    request_detector: AnomalyDetector | None = None,
                    ) -> None:
        """Turn on outlier detection over step durations and request
        latencies; with a spool, each actionable anomaly snapshots the
        current trace window plus the slowest flight records."""
        self._step_detector = step_detector or AnomalyDetector()
        self._request_detector = request_detector or AnomalyDetector()
        self.spool = spool
        if tracer is not None:
            self.arm_tracer(tracer)
        self._flight = flight

    # -- recording ----------------------------------------------------------

    def _add(self, ch: str, v: float, n: int = 1) -> None:
        now = self._clock()
        for ring in self._lat[ch].values():
            ring.add(now, v, n)

    def _add_rate(self, ch: str, num: int, den: int) -> None:
        now = self._clock()
        for ring in self._rate[ch].values():
            ring.add(now, num, den)

    def record_ttft(self, v: float) -> None:
        self._add("ttft", v)

    def record_itl(self, v: float, n: int = 1) -> None:
        self._add("itl", v, n)

    def record_queue_wait(self, v: float) -> None:
        self._add("queue_wait", v)

    def record_accept(self, accepted: int, proposed: int) -> None:
        self._add_rate("accept", int(accepted), int(proposed))

    def record_deadline(self, met: bool) -> None:
        self._add_rate("deadline", 1 if met else 0, 1)

    def record_finish(self, ok: bool) -> None:
        """One finished request: ok=True for natural finishes
        (eos/length), False for errors (quarantine, deadline, abort) —
        the availability objective's sample."""
        self._add_rate("availability", 1 if ok else 0, 1)

    def record_step(self, v: float) -> None:
        self._add("step", v)
        det = self._step_detector
        if det is not None and det.observe(v):
            self._capture("slow_step", det)

    def record_request(self, v: float) -> None:
        """One finished request's total latency (admission to last
        token) — the slow-request anomaly signal."""
        self._add("request", v)
        det = self._request_detector
        if det is not None and det.observe(v):
            self._capture("slow_request", det)

    # -- anomaly capture ----------------------------------------------------

    def anomalies_detected(self) -> int:
        n = 0
        for det in (self._step_detector, self._request_detector):
            if det is not None:
                n += det.detected
        return n

    def _capture(self, kind: str, det: AnomalyDetector) -> None:
        spool = self.spool
        if spool is None:
            return
        payload = {"kind": kind, **det.last}
        tr = self._tracer
        if tr is not None:
            payload["trace"] = tr.chrome_trace()
        fl = self._flight
        if fl is not None:
            payload["flight"] = fl.list(sort="slowest", limit=8)
        spool.capture(payload)

    # -- reading ------------------------------------------------------------

    def snapshot(self, now: float | None = None) -> dict:
        """Per-window view: for each window label, non-cumulative
        bucket counts (on the shared ladder, summable across
        replicas), sum/count, p50/p95/p99 per latency channel, and
        num/den/rate per rate channel."""
        if now is None:
            now = self._clock()
        out: dict = {"bounds": list(_BOUNDS)}
        for w in self.windows:
            label = _wlabel(w)
            wd: dict = {}
            for ch in _LATENCY_CHANNELS:
                counts, total, n = self._lat[ch][label].merged(now)
                wd[ch] = {
                    "buckets": counts, "sum": round(total, 6), "count": n,
                    "p50_ms": round(1e3 * bucket_percentile(counts, 50), 3),
                    "p95_ms": round(1e3 * bucket_percentile(counts, 95), 3),
                    "p99_ms": round(1e3 * bucket_percentile(counts, 99), 3),
                }
            for ch in _RATE_CHANNELS:
                num, den = self._rate[ch][label].merged(now)
                wd[ch] = {"num": num, "den": den,
                          "rate": round(num / den, 4) if den else 0.0}
            out[label] = wd
        return out

    def snapshot_keys(self) -> dict:
        """The keys ``ServingStats.snapshot()`` merges in when windows
        are enabled: the nested per-window dict, the SLO evaluation,
        headline flat scalars, and the anomaly counters."""
        ws = self.snapshot()
        ev = self.slo.evaluate(ws)
        mid = _wlabel(self.windows[min(1, len(self.windows) - 1)])
        spool = self.spool
        return {
            "windows": ws,
            "slo": ev,
            "slo_state": ev["state"],
            "slo_state_name": ev["state_name"],
            "ttft_p95_w60s": ws[mid]["ttft"]["p95_ms"],
            "itl_p99_w60s": ws[mid]["itl"]["p99_ms"],
            "queue_wait_p95_w60s": ws[mid]["queue_wait"]["p95_ms"],
            "anomalies_detected": self.anomalies_detected(),
            "anomalies_captured": spool.captured if spool else 0,
            "anomaly_spool_dropped": spool.dropped if spool else 0,
        }


def aggregate_windows(window_snapshots) -> dict:
    """Pool per-replica ``WindowedTelemetry.snapshot()`` dicts into one
    fleet view: bucket counts sum index-by-index per (window, channel)
    — identical ladders make this exact — sums/counts add, rate
    channels add num/den, and percentiles are recomputed from the
    POOLED distribution (honest fleet quantiles, not max-of-replicas).
    """
    snaps = [w for w in window_snapshots if w]
    if not snaps:
        return {}
    out: dict = {"bounds": list(snaps[0]["bounds"])}
    labels = [k for k in snaps[0] if k != "bounds"]
    for label in labels:
        wd: dict = {}
        for ch in _LATENCY_CHANNELS:
            nb = len(snaps[0]["bounds"]) + 1
            counts = [0] * nb
            total = 0.0
            n = 0
            for s in snaps:
                c = s.get(label, {}).get(ch)
                if not c:
                    continue
                for j, cj in enumerate(c["buckets"]):
                    counts[j] += cj
                total += c["sum"]
                n += c["count"]
            wd[ch] = {
                "buckets": counts, "sum": round(total, 6), "count": n,
                "p50_ms": round(1e3 * bucket_percentile(counts, 50), 3),
                "p95_ms": round(1e3 * bucket_percentile(counts, 95), 3),
                "p99_ms": round(1e3 * bucket_percentile(counts, 99), 3),
            }
        for ch in _RATE_CHANNELS:
            num = den = 0
            for s in snaps:
                c = s.get(label, {}).get(ch)
                if not c:
                    continue
                num += c["num"]
                den += c["den"]
            wd[ch] = {"num": num, "den": den,
                      "rate": round(num / den, 4) if den else 0.0}
        out[label] = wd
    return out
