"""Throughput benchmark timer (reference python/paddle/profiler/timer.py).

benchmark() returns the global Benchmark: begin()/step(n)/end() bracket the
train loop and step_info() reports reader cost, batch cost and ips
(items/sec) — the meter used for the BASELINE.md perf numbers.
"""
from __future__ import annotations

import time

__all__ = ["benchmark", "Benchmark"]


class _Stat:
    def __init__(self):
        self.total = 0.0
        self.count = 0
        self.window_total = 0.0
        self.window_count = 0

    def add(self, v):
        self.total += v
        self.count += 1
        self.window_total += v
        self.window_count += 1

    def reset_window(self):
        self.window_total = 0.0
        self.window_count = 0

    @property
    def avg(self):
        return self.total / self.count if self.count else 0.0

    @property
    def window_avg(self):
        return (self.window_total / self.window_count
                if self.window_count else 0.0)


class Benchmark:
    def __init__(self):
        self.reset()

    def reset(self):
        self._last = None
        self._reader_mark = None
        self.batch_cost = _Stat()
        self.reader_cost = _Stat()
        self._samples = 0
        self._window_samples = 0
        self._running = False

    # hooks called by DataLoader to attribute reader time
    def before_reader(self):
        self._reader_mark = time.perf_counter()

    def after_reader(self):
        if self._reader_mark is not None and self._running:
            self.reader_cost.add(time.perf_counter() - self._reader_mark)
            self._reader_mark = None

    def begin(self):
        self._running = True
        self._last = time.perf_counter()

    def step(self, num_samples=None):
        if not self._running:
            self.begin()
        now = time.perf_counter()
        self.batch_cost.add(now - self._last)
        self._last = now
        if num_samples:
            self._samples += num_samples
            self._window_samples += num_samples

    def end(self):
        self._running = False

    @property
    def ips(self):
        """Items/sec over the current window (falls back to steps/sec)."""
        t = self.batch_cost.window_total
        if t <= 0:
            return 0.0
        n = self._window_samples or self.batch_cost.window_count
        return n / t

    def step_info(self, unit=None):
        u = unit or "samples"
        msg = (f"reader_cost: {self.reader_cost.window_avg:.5f} s, "
               f"batch_cost: {self.batch_cost.window_avg:.5f} s, "
               f"ips: {self.ips:.3f} {u}/s")
        self.batch_cost.reset_window()
        self.reader_cost.reset_window()
        self._window_samples = 0
        return msg


_benchmark = Benchmark()


def benchmark() -> Benchmark:
    return _benchmark
