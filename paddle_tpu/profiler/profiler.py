"""Profiler core: scheduler-driven host tracer + chrome-trace export.

Reference call shape (python/paddle/profiler/profiler.py):
    p = Profiler(targets=[...], scheduler=(2, 5), on_trace_ready=...)
    p.start(); loop: train_step(); p.step(); ...; p.stop()
    p.summary()
"""
from __future__ import annotations

import json
import os
import threading
import time
from enum import Enum

__all__ = ["Profiler", "ProfilerState", "ProfilerTarget", "RecordEvent",
           "make_scheduler", "export_chrome_tracing", "load_profiler_result"]


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1          # accepted for API compat; maps to the TPU device trace
    TPU = 1
    CUSTOM_DEVICE = 2


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0):
    """Reference make_scheduler: step -> ProfilerState cycle
    [CLOSED]*closed -> [READY]*ready -> [RECORD]*(record-1) ->
    RECORD_AND_RETURN, repeated `repeat` times (0 = forever)."""
    if closed < 0 or ready < 0:
        raise ValueError("closed/ready must be >= 0")
    if record < 1:
        raise ValueError("record must be >= 1 (each cycle needs at least "
                         "the RECORD_AND_RETURN step)")
    period = closed + ready + record

    def scheduler_fn(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        step -= skip_first
        cycle = step // period
        if repeat and cycle >= repeat:
            return ProfilerState.CLOSED
        pos = step % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos < period - 1:
            return ProfilerState.RECORD
        return ProfilerState.RECORD_AND_RETURN

    return scheduler_fn


def _default_scheduler(step: int) -> ProfilerState:
    return ProfilerState.RECORD


def export_chrome_tracing(dir_name: str, worker_name: str | None = None):
    """on_trace_ready callback writing chrome://tracing JSON
    (reference profiler.py:227)."""
    os.makedirs(dir_name, exist_ok=True)

    seq = [0]

    def handler(prof: "Profiler"):
        name = worker_name or f"host_{os.getpid()}"
        # ns timestamp + per-handler sequence: cycles flushed within the
        # same second must not overwrite each other
        path = os.path.join(
            dir_name,
            f"{name}_time_{time.time_ns()}_{seq[0]}.paddle_trace.json")
        seq[0] += 1
        prof._export_chrome(path)
        prof._last_export_path = path

    return handler


def load_profiler_result(file_name: str):
    with open(file_name) as f:
        return json.load(f)


class _HostTracer:
    """Collects (name, start_ns, dur_ns, tid) host events."""

    def __init__(self, max_events: int = 1_000_000):
        self.events = []
        self.enabled = False
        # per-cycle cap: events clear on every cycle boundary, but a
        # runaway RECORD span must not grow the host heap without bound;
        # overflow is counted, not silent
        self.max_events = max_events
        self.dropped = 0
        self._lock = threading.Lock()

    def add(self, name, start_ns, dur_ns):
        if not self.enabled:
            return
        with self._lock:
            if len(self.events) >= self.max_events:
                self.dropped += 1
                return
            self.events.append(
                (name, start_ns, dur_ns, threading.get_ident()))


_active_tracer: _HostTracer | None = None


class RecordEvent:
    """Host annotation context manager (reference utils.py RecordEvent);
    also mirrored into the device trace via jax.profiler.TraceAnnotation."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._jax_ann = None
        self._begin_ns = None

    def begin(self):
        # Always emit the device-trace annotation: a user-driven
        # jax.profiler.start_trace must still see RecordEvent markers even
        # with no host Profiler active (TraceMe is ~free when no device
        # trace is running).  Host-event bookkeeping only runs while a
        # Profiler records.
        try:
            import jax.profiler
            self._jax_ann = jax.profiler.TraceAnnotation(self.name)
            self._jax_ann.__enter__()
        except Exception:
            self._jax_ann = None
        if _active_tracer is None:
            return
        self._begin_ns = time.perf_counter_ns()

    def end(self):
        if self._jax_ann is not None:
            self._jax_ann.__exit__(None, None, None)
            self._jax_ann = None
        if self._begin_ns is not None and _active_tracer is not None:
            _active_tracer.add(self.name, self._begin_ns,
                               time.perf_counter_ns() - self._begin_ns)
        self._begin_ns = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


class Profiler:
    """Scheduler-driven profiler (reference profiler.py:358)."""

    def __init__(self, *, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=False, custom_device_types=None):
        if scheduler is None:
            self._scheduler = _default_scheduler
        elif callable(scheduler):
            self._scheduler = scheduler
        elif isinstance(scheduler, (tuple, list)) and len(scheduler) == 2:
            start, end = scheduler
            self._scheduler = make_scheduler(
                closed=max(start - 1, 0), ready=(1 if start >= 1 else 0),
                record=end - start, repeat=1)
        else:
            raise TypeError("scheduler must be callable or (start, end)")
        self._targets = list(targets or [ProfilerTarget.CPU])
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self._tracer = _HostTracer()
        self._step = 0
        self._state = ProfilerState.CLOSED
        self._device_trace_dir = None
        self._device_tracing = False
        self._last_export_path = None
        from .timer import benchmark
        self._benchmark = benchmark()

    # -- state machinery --------------------------------------------------
    def _transition(self, new_state: ProfilerState):
        global _active_tracer
        from ..core import dispatch as _dispatch
        old = self._state
        # RECORD_AND_RETURN is the LAST record step of a cycle: close it out
        # whatever comes next (back-to-back cycles included)
        if old is ProfilerState.RECORD_AND_RETURN:
            self._finish_record()
        if new_state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            if old is not ProfilerState.RECORD:
                self._tracer.events.clear()
            self._tracer.enabled = True
            _active_tracer = self._tracer
            # per-op host spans from the eager dispatch hot loop
            _dispatch._op_observer = self._tracer.add
            self._maybe_start_device_trace()
        else:
            if old is ProfilerState.RECORD:  # e.g. stop() mid-cycle
                self._finish_record()
            self._tracer.enabled = False
            _active_tracer = None
            _dispatch._op_observer = None
        self._state = new_state

    def _maybe_start_device_trace(self):
        if self._timer_only or self._device_tracing:
            return
        want_device = any(t != ProfilerTarget.CPU for t in self._targets)
        if not want_device:
            return
        try:
            import jax.profiler
            self._device_trace_dir = (self._device_trace_dir
                                      or os.path.join(os.getcwd(),
                                                      "profiler_log"))
            jax.profiler.start_trace(self._device_trace_dir)
            self._device_tracing = True
        except Exception:
            self._device_tracing = False

    def _finish_record(self):
        if self._device_tracing:
            try:
                import jax.profiler
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._device_tracing = False
        if self._on_trace_ready is not None:
            self._on_trace_ready(self)

    # -- public API -------------------------------------------------------
    def start(self):
        self._benchmark.begin()
        self._transition(self._scheduler(self._step))

    def step(self, num_samples=None):
        self._benchmark.step(num_samples)
        self._step += 1
        self._transition(self._scheduler(self._step))

    def stop(self):
        self._benchmark.end()
        self._transition(ProfilerState.CLOSED)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def step_info(self, unit=None):
        return self._benchmark.step_info(unit)

    # -- results ----------------------------------------------------------
    def events(self):
        return list(self._tracer.events)

    def _export_chrome(self, path):
        trace_events = []
        for name, start_ns, dur_ns, tid in self._tracer.events:
            trace_events.append({
                "ph": "X", "cat": "host", "name": name,
                "ts": start_ns / 1000.0, "dur": dur_ns / 1000.0,
                "pid": os.getpid(), "tid": tid,
            })
        with open(path, "w") as f:
            json.dump({"traceEvents": trace_events,
                       "displayTimeUnit": "ms"}, f)

    def export(self, path, format="json"):
        self._export_chrome(path)

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        """Aggregate host events by name (reference profiler_statistic
        table, condensed)."""
        agg = {}
        for name, _, dur_ns, _ in self._tracer.events:
            tot, cnt, mx = agg.get(name, (0.0, 0, 0.0))
            agg[name] = (tot + dur_ns, cnt + 1, max(mx, dur_ns))
        unit_div = {"ms": 1e6, "us": 1e3, "s": 1e9}[time_unit]
        lines = [f"{'Name':<40}{'Calls':>8}{'Total(' + time_unit + ')':>14}"
                 f"{'Avg(' + time_unit + ')':>12}{'Max(' + time_unit + ')':>12}"]
        for name, (tot, cnt, mx) in sorted(agg.items(),
                                           key=lambda kv: -kv[1][0]):
            lines.append(f"{name[:39]:<40}{cnt:>8}{tot / unit_div:>14.3f}"
                         f"{tot / cnt / unit_div:>12.3f}{mx / unit_div:>12.3f}")
        table = "\n".join(lines)
        print(table)
        return table
