"""Step-timeline tracing: a bounded ring buffer of serving-tier spans.

``ServingStats`` (profiler/serving.py) answers "how fast is the stream";
this module answers "where did one step's time go".  A ``Tracer`` is a
fixed-capacity ring buffer of event TUPLES — span begin/duration pairs,
instant markers, and async request-lifecycle begin/end — stamped with
``time.perf_counter_ns`` and a logical TRACK (one per serving tier:
engine, runner, router, http), exported as Chrome trace-event JSON that
Perfetto (https://ui.perfetto.dev) loads directly.

Design rules, in the order they constrain the code:

* **Disabled means free.**  The tracer is opt-in; every instrumentation
  site guards on ``tracer is None`` FIRST (mirroring FaultPlan's seam
  contract), so an engine without a tracer pays one attribute check per
  phase and allocates nothing — pinned by test via tracemalloc filtering
  on this file.
* **Bounded forever.**  Events land in a deque capped at ``capacity``;
  when full the OLDEST event is dropped and ``dropped`` counts it, so a
  server tracing for days holds the most recent window and reports
  exactly how much history it shed.  ``serve_bench`` records the drop
  counter next to its perf numbers.
* **Cheap hot path.**  An event is one tuple append under one small
  lock.  Timestamps are integer nanoseconds from ``perf_counter_ns``
  (monotonic, never wall-clock — see the ``wallclock-in-timing-path``
  lint rule); conversion to chrome's microsecond floats happens only at
  export.
* **Spans nest per thread.**  ``span()`` is a context manager that
  pushes/pops a per-thread stack; exits must match enters (violations
  are counted in ``unbalanced``, never raised mid-serve).  Code that
  yields mid-section (asyncio handlers) uses the stackless
  ``now()``/``complete()`` pair instead, so one coroutine's section
  cannot corrupt another's stack.

Export shape: ``chrome_trace()`` returns a JSON-ready dict whose
``traceEvents`` hold "X" (complete) events for spans, "i" for instants,
"b"/"e" async pairs (cat="request") for request lifecycles — the async
id carries the engine track + rid, and runner delivery instants carry
both the engine rid and the frontend request id, so one request is
followable across all four tiers.  Thread-name metadata maps each track
to its own row in the viewer.
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque

__all__ = ["Tracer"]


class _Span:
    """One ``with tracer.span(...)`` section.  Captures t0 as late as
    possible on enter and emits a single "X" event on exit."""

    __slots__ = ("_tr", "_name", "_track", "_args", "_t0")

    def __init__(self, tr, name, track, args):
        self._tr = tr
        self._name = name
        self._track = track
        self._args = args

    def __enter__(self):
        self._tr._stack().append(self._name)
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter_ns()
        tr = self._tr
        stack = tr._stack()
        if not stack or stack.pop() != self._name:
            tr.unbalanced += 1
        tr._push(("X", self._name, self._t0, t1 - self._t0,
                  tr._tid(self._track), self._args, None))
        return False


class Tracer:
    """Bounded ring buffer of serving spans, Perfetto-exportable.

    Parameters
    ----------
    capacity: maximum events held.  The buffer keeps the most RECENT
        window; older events drop oldest-first into ``dropped``.
    """

    def __init__(self, capacity: int = 65536):
        self.capacity = max(1, int(capacity))
        self._events: deque = deque()
        self.dropped = 0              # events shed by the ring bound
        self.unbalanced = 0           # span exits that missed their enter
        self._lock = threading.Lock()
        self._tracks: dict = {}       # track name -> tid (viewer row)
        self._local = threading.local()
        self.t0_ns = time.perf_counter_ns()   # trace epoch

    # -- clock + tracks -----------------------------------------------------

    @staticmethod
    def now() -> int:
        """Integer-nanosecond monotonic timestamp (pair with
        ``complete()`` for sections that yield mid-way)."""
        return time.perf_counter_ns()

    def register(self, base: str) -> str:
        """Reserve a unique track name ("engine", "engine-2", ...).
        Each tier registers once and stamps its events with the result,
        so two replicas' engines land on separate viewer rows."""
        with self._lock:
            name = base
            n = 2
            while name in self._tracks:
                name = f"{base}-{n}"
                n += 1
            self._tracks[name] = len(self._tracks) + 1
        return name

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _tid(self, track) -> int:
        if track is None:
            track = getattr(self._local, "track", None)
            if track is None:
                track = self.register(
                    f"host:{threading.current_thread().name}")
                self._local.track = track
        tid = self._tracks.get(track)
        if tid is None:
            with self._lock:
                tid = self._tracks.setdefault(track,
                                              len(self._tracks) + 1)
        return tid

    # -- recording ----------------------------------------------------------

    def _push(self, ev: tuple) -> None:
        with self._lock:
            if len(self._events) >= self.capacity:
                self._events.popleft()
                self.dropped += 1
            self._events.append(ev)

    def span(self, name: str, track: str | None = None, **args) -> _Span:
        """Context manager for one duration span on this thread's stack.
        Do NOT hold one across an ``await`` — use ``now()``/``complete()``
        there instead."""
        return _Span(self, name, track, args or None)

    def complete(self, name: str, t0_ns: int, track: str | None = None,
                 args: dict | None = None) -> None:
        """Record a span that started at ``t0_ns`` (from ``now()``) and
        ends now.  Stackless: safe from coroutines and guarded hot
        loops."""
        t1 = time.perf_counter_ns()
        self._push(("X", name, t0_ns, t1 - t0_ns, self._tid(track),
                    args, None))

    def instant(self, name: str, track: str | None = None,
                args: dict | None = None) -> None:
        self._push(("i", name, time.perf_counter_ns(), 0,
                    self._tid(track), args, None))

    def async_begin(self, name: str, ev_id: str,
                    args: dict | None = None) -> None:
        """Open one request-lifecycle track (chrome "b" event, matched
        to its "e" by (cat, name, id))."""
        self._push(("b", name, time.perf_counter_ns(), 0,
                    self._tid(None), args, str(ev_id)))

    def async_end(self, name: str, ev_id: str,
                  args: dict | None = None) -> None:
        self._push(("e", name, time.perf_counter_ns(), 0,
                    self._tid(None), args, str(ev_id)))

    # -- reading ------------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def events(self) -> list:
        """Snapshot of the raw event tuples
        (ph, name, ts_ns, dur_ns, tid, args, id), oldest first."""
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0
            self.unbalanced = 0

    def chrome_trace(self) -> dict:
        """The trace as a Chrome trace-event JSON object (Perfetto's
        "open file" format): thread-name metadata per track, events
        sorted by timestamp, microsecond floats relative to the trace
        epoch.  Drop accounting rides in ``otherData``."""
        with self._lock:
            events = sorted(self._events, key=lambda e: e[2])
            tracks = dict(self._tracks)
            dropped = self.dropped
            unbalanced = self.unbalanced
        te = [{"ph": "M", "name": "process_name", "pid": 1, "tid": 0,
               "args": {"name": "paddle_tpu.serving"}}]
        for name, tid in sorted(tracks.items(), key=lambda kv: kv[1]):
            te.append({"ph": "M", "name": "thread_name", "pid": 1,
                       "tid": tid, "args": {"name": name}})
            te.append({"ph": "M", "name": "thread_sort_index", "pid": 1,
                       "tid": tid, "args": {"sort_index": tid}})
        t0 = self.t0_ns
        for ph, name, ts, dur, tid, args, ev_id in events:
            ev = {"ph": ph, "name": name, "pid": 1, "tid": tid,
                  "ts": (ts - t0) / 1e3}
            if ph == "X":
                ev["dur"] = dur / 1e3
            elif ph == "i":
                ev["s"] = "t"
            elif ph in ("b", "e"):
                ev["cat"] = "request"
                ev["id"] = ev_id
            if args:
                ev["args"] = args
            te.append(ev)
        return {"traceEvents": te, "displayTimeUnit": "ms",
                "otherData": {"dropped_events": dropped,
                              "unbalanced_spans": unbalanced,
                              "clock": "perf_counter_ns"}}

    def dump(self, path) -> int:
        """Write ``chrome_trace()`` to ``path``; returns the number of
        non-metadata events written."""
        with self._lock:
            n = len(self._events)
        doc = self.chrome_trace()
        with open(path, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        return n
