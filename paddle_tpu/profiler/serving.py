"""Serving-side profiling: per-step timing + scheduler counters.

The LLM engine (paddle_tpu/inference/serving.py) is a host loop around two
compiled programs; what matters for serving perf is not one op's latency
but the shape of the whole stream — per-token latency percentiles, how
full the decode batch ran, how often the page pool forced a preemption,
and how many distinct programs XLA had to build.  ``ServingStats``
aggregates exactly that, and the engine additionally brackets each phase
in ``profiler.RecordEvent`` so engine steps land in chrome traces next to
model ops when a Profiler is active.
"""
from __future__ import annotations

__all__ = ["ServingStats"]


def _percentile(sorted_vals, q: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


class ServingStats:
    """Aggregates one serving run's step timings and scheduler events.

    Times arrive from the engine as wall-clock seconds per STEP together
    with how many sequences' tokens that step produced; per-token latency
    is the step duration each of those tokens observed (every sequence in
    a batched step waits for the whole step).
    """

    def __init__(self):
        self.reset()

    def reset(self):
        self.prefill_steps = 0
        self.prefill_tokens = 0          # prompt tokens processed
        self.prefill_time = 0.0
        self.decode_steps = 0
        self.decode_tokens = 0           # tokens emitted by decode steps
        self.decode_time = 0.0
        self._token_lat = []             # per emitted token: its step's dur
        self._occupancy = []             # running/max_num_seqs per decode step
        self.preemptions = 0
        self.admitted = 0
        self.retired = 0
        # prefix-cache + chunked-prefill surface (PR 2)
        self.cache_hit_tokens = 0        # prompt tokens served from cache
        self.cache_miss_tokens = 0       # prompt tokens prefilled fresh
        self.cow_copies = 0              # copy-on-write page copies
        self.cache_evictions = 0         # cached pages reclaimed under pressure
        self._prefill_queue = []         # per step: requests with pending prefill
        self._ttft = []                  # per request: arrival -> first token (s)
        # speculative decoding surface (PR 4)
        self.verify_steps = 0            # verify program launches
        self.verify_time = 0.0
        self.spec_rounds = 0             # (sequence, verify) acceptance rounds
        self.draft_proposed = 0          # draft tokens sent to verify
        self.draft_accepted = 0          # draft tokens that survived (hits)
        self.spec_emitted_tokens = 0     # tokens emitted by verify steps
        self.rollback_tokens = 0         # draft tokens rolled back
        self.rollback_pages = 0          # pages released by truncate
        self.spec_disables = 0           # requests whose speculation tripped off

    # -- recording (engine-facing) ------------------------------------------

    def record_prefill(self, duration_s: float, n_prompt_tokens: int,
                       n_seqs: int) -> None:
        self.prefill_steps += 1
        self.prefill_tokens += int(n_prompt_tokens)
        self.prefill_time += float(duration_s)
        # each sequence's first token comes out of the prefill step
        self._token_lat.extend([float(duration_s)] * int(n_seqs))

    def record_decode(self, duration_s: float, n_tokens: int,
                      occupancy: float) -> None:
        self.decode_steps += 1
        self.decode_tokens += int(n_tokens)
        self.decode_time += float(duration_s)
        self._token_lat.extend([float(duration_s)] * int(n_tokens))
        self._occupancy.append(float(occupancy))

    def record_admission(self, n: int = 1) -> None:
        self.admitted += int(n)

    def record_retirement(self, n: int = 1) -> None:
        self.retired += int(n)

    def record_preemption(self, n: int = 1) -> None:
        self.preemptions += int(n)

    def record_cache_lookup(self, hit_tokens: int, miss_tokens: int) -> None:
        """One admission's prefix-cache match: how many prompt tokens the
        cache already held vs how many must be prefilled."""
        self.cache_hit_tokens += int(hit_tokens)
        self.cache_miss_tokens += int(miss_tokens)

    def record_cow(self, n: int = 1) -> None:
        self.cow_copies += int(n)

    def record_evictions(self, n: int = 1) -> None:
        self.cache_evictions += int(n)

    def record_prefill_queue(self, depth: int) -> None:
        """Requests (running or waiting) with prompt tokens still to
        prefill at this step — the chunked-prefill backlog."""
        self._prefill_queue.append(int(depth))

    def record_ttft(self, duration_s: float) -> None:
        self._ttft.append(float(duration_s))

    def record_verify(self, duration_s: float, n_tokens: int,
                      occupancy: float) -> None:
        """One verify-program launch that emitted n_tokens across its
        speculative sequences.  The tokens count as decode output (that
        is what they replace) so tok/s comparisons stay apples-to-apples
        with speculation off."""
        self.verify_steps += 1
        self.verify_time += float(duration_s)
        self.decode_tokens += int(n_tokens)
        self.decode_time += float(duration_s)
        self._token_lat.extend([float(duration_s)] * int(n_tokens))
        self._occupancy.append(float(occupancy))

    def record_spec(self, *, proposed: int, accepted: int, emitted: int,
                    rollback: int, pages_rolled: int = 0) -> None:
        """One sequence's acceptance round inside a verify step."""
        self.spec_rounds += 1
        self.draft_proposed += int(proposed)
        self.draft_accepted += int(accepted)
        self.spec_emitted_tokens += int(emitted)
        self.rollback_tokens += int(rollback)
        self.rollback_pages += int(pages_rolled)

    def record_spec_disable(self, n: int = 1) -> None:
        self.spec_disables += int(n)

    # -- derived metrics ----------------------------------------------------

    def decode_tokens_per_s(self) -> float:
        return self.decode_tokens / self.decode_time if self.decode_time \
            else 0.0

    def token_latency_ms(self, q: float) -> float:
        return 1e3 * _percentile(sorted(self._token_lat), q)

    def mean_occupancy(self) -> float:
        return sum(self._occupancy) / len(self._occupancy) \
            if self._occupancy else 0.0

    def prefix_hit_rate(self) -> float:
        total = self.cache_hit_tokens + self.cache_miss_tokens
        return self.cache_hit_tokens / total if total else 0.0

    def ttft_ms(self, q: float) -> float:
        return 1e3 * _percentile(sorted(self._ttft), q)

    def accept_rate(self) -> float:
        return self.draft_accepted / self.draft_proposed \
            if self.draft_proposed else 0.0

    def summary(self) -> dict:
        return {
            "prefill_steps": self.prefill_steps,
            "prefill_tokens": self.prefill_tokens,
            "decode_steps": self.decode_steps,
            "decode_tokens": self.decode_tokens,
            "decode_tokens_per_s": round(self.decode_tokens_per_s(), 2),
            "p50_token_ms": round(self.token_latency_ms(50), 3),
            "p99_token_ms": round(self.token_latency_ms(99), 3),
            "mean_batch_occupancy": round(self.mean_occupancy(), 4),
            "admitted": self.admitted,
            "retired": self.retired,
            "preemptions": self.preemptions,
            "cache_hit_tokens": self.cache_hit_tokens,
            "cache_miss_tokens": self.cache_miss_tokens,
            "prefix_hit_rate": round(self.prefix_hit_rate(), 4),
            "prefill_tokens_saved": self.cache_hit_tokens,
            "cow_copies": self.cow_copies,
            "cache_evictions": self.cache_evictions,
            "mean_prefill_queue_depth": round(
                sum(self._prefill_queue) / len(self._prefill_queue), 3)
            if self._prefill_queue else 0.0,
            "max_prefill_queue_depth": max(self._prefill_queue, default=0),
            "ttft_p50_ms": round(self.ttft_ms(50), 3),
            "ttft_p99_ms": round(self.ttft_ms(99), 3),
            "verify_steps": self.verify_steps,
            "spec_rounds": self.spec_rounds,
            "draft_proposed": self.draft_proposed,
            "draft_accepted": self.draft_accepted,
            "accept_rate": round(self.accept_rate(), 4),
            "spec_emitted_tokens": self.spec_emitted_tokens,
            "rollback_tokens": self.rollback_tokens,
            "rollback_pages": self.rollback_pages,
            "spec_disables": self.spec_disables,
        }
