"""Serving-side profiling: per-step timing + scheduler counters.

The LLM engine (paddle_tpu/inference/serving.py) is a host loop around a
handful of compiled programs; what matters for serving perf is not one
op's latency but the shape of the whole stream — per-token latency
percentiles, how full the decode batch ran, how often the page pool
forced a preemption, and how many distinct programs XLA had to build.
``ServingStats`` aggregates exactly that, and the engine additionally
brackets each phase in ``profiler.RecordEvent`` so engine steps land in
chrome traces next to model ops when a Profiler is active.

A server that stays up for days must not let its stats surface grow with
traffic: every distribution (per-token latency, TTFT, batch occupancy,
prefill queue depth) lives in a bounded RESERVOIR — counters and sums are
exact, percentiles are computed on demand from a uniform sample of fixed
size (Vitter's Algorithm R, deterministic replacement) — so memory is
O(reservoir) no matter how many requests pass through.
``ServingStats.snapshot()`` is the one read surface: the HTTP frontend's
``/metrics`` endpoint and ``tools/perf/serve_bench.py`` both render it.
Reservoir mutation and sampling take a tiny per-reservoir lock, so the
frontend thread can snapshot while the engine thread records.
"""
from __future__ import annotations

import bisect
import random
import threading
import time

__all__ = ["ServingStats"]


def _percentile(sorted_vals, q: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


class _Reservoir:
    """Bounded uniform sample of a value stream (Vitter's Algorithm R).

    The first ``capacity`` values are kept verbatim (small runs — every
    test and bench below capacity — get EXACT percentiles); after that
    each new value replaces a uniformly-chosen slot with probability
    capacity/n, keeping the sample uniform over the whole stream.  The
    RNG is seeded per reservoir, so a rerun of the same stream reproduces
    the same sample.  count/total/vmin/vmax stay exact regardless.
    """

    __slots__ = ("capacity", "count", "total", "vmin", "vmax",
                 "_sample", "_rng", "_lock")

    def __init__(self, capacity: int = 2048, seed: int = 0):
        self.capacity = int(capacity)
        self._rng = random.Random(0x5EED ^ seed)
        self._lock = threading.Lock()
        self.count = 0
        self.total = 0.0
        self.vmin = 0.0
        self.vmax = 0.0
        self._sample = []

    def add(self, value: float) -> None:
        v = float(value)
        with self._lock:
            if self.count == 0:
                self.vmin = self.vmax = v
            else:
                self.vmin = min(self.vmin, v)
                self.vmax = max(self.vmax, v)
            self.count += 1
            self.total += v
            if len(self._sample) < self.capacity:
                self._sample.append(v)
            else:
                j = self._rng.randrange(self.count)
                if j < self.capacity:
                    self._sample[j] = v

    def extend(self, value: float, n: int) -> None:
        for _ in range(int(n)):
            self.add(value)

    def percentile(self, q: float) -> float:
        with self._lock:
            vals = sorted(self._sample)
        return _percentile(vals, q)

    def samples(self) -> list:
        """Copy of the current sample — the fleet aggregator pools these
        across replicas and recomputes percentiles over the union."""
        with self._lock:
            return list(self._sample)

    def mean(self) -> float:
        with self._lock:
            return self.total / self.count if self.count else 0.0

    def __len__(self) -> int:
        with self._lock:
            return self.count


# Prometheus-style latency bucket bounds in SECONDS — one shared ladder
# for TTFT/ITL/step-duration so fleet aggregation can sum bucket counts
# replica-by-replica (cumulative counts with identical bounds add).
_HIST_BOUNDS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class _Hist:
    """Fixed-bound latency histogram (exact counts, unlike the
    reservoirs): per-bucket tallies plus total sum/count, rendered on
    ``/metrics`` as a real Prometheus histogram series (``_bucket{le=}``
    cumulative counts + ``_sum`` + ``_count``) next to the quantile
    gauges.  ``le`` is inclusive, matching Prometheus semantics."""

    __slots__ = ("bounds", "_counts", "total", "count", "_lock")

    def __init__(self, bounds=_HIST_BOUNDS):
        self.bounds = tuple(float(b) for b in bounds)
        self._counts = [0] * (len(self.bounds) + 1)   # last = +Inf
        self.total = 0.0
        self.count = 0
        self._lock = threading.Lock()

    def add(self, value: float, n: int = 1) -> None:
        v = float(value)
        n = int(n)
        i = bisect.bisect_left(self.bounds, v)   # v <= bounds[i] -> bucket i
        with self._lock:
            self._counts[i] += n
            self.count += n
            self.total += v * n

    def buckets(self) -> dict:
        """Cumulative counts keyed by upper bound ("0.005" ... "+Inf")."""
        with self._lock:
            counts = list(self._counts)
        out: dict = {}
        c = 0
        for b, n in zip(self.bounds, counts):
            c += n
            out[f"{b:g}"] = c
        out["+Inf"] = c + counts[-1]
        return out


class ServingStats:
    """Aggregates one serving run's step timings and scheduler events.

    Times arrive from the engine as wall-clock seconds per STEP together
    with how many sequences' tokens that step produced; per-token latency
    is the step duration each of those tokens observed (every sequence in
    a batched step waits for the whole step) — the stream's inter-token
    latency (ITL).  TTFT is recorded per request at its first emitted
    token.  All distributions are reservoir-bounded; ``snapshot()``
    (aliased ``summary()``) is the canonical read surface.
    """

    RESERVOIR = 2048

    def __init__(self, reservoir: int = RESERVOIR):
        self._reservoir = int(reservoir)
        self.reset()

    def reset(self):
        r = self._reservoir
        self.prefill_steps = 0
        self.prefill_tokens = 0          # prompt tokens processed
        self.prefill_time = 0.0
        self.decode_steps = 0
        self.decode_tokens = 0           # tokens emitted by decode steps
        self.decode_time = 0.0
        self._token_lat = _Reservoir(r, seed=1)   # ITL: per-token step dur
        self._occupancy = _Reservoir(r, seed=2)   # running/max per decode
        self.preemptions = 0
        self.admitted = 0
        self.retired = 0
        # prefix-cache + chunked-prefill surface (PR 2)
        self.cache_hit_tokens = 0        # prompt tokens served from cache
        self.cache_miss_tokens = 0       # prompt tokens prefilled fresh
        self.cow_copies = 0              # copy-on-write page copies
        self.cache_evictions = 0         # cached pages reclaimed under pressure
        self._prefill_queue = _Reservoir(r, seed=3)  # pending-prefill depth
        self._ttft = _Reservoir(r, seed=4)   # arrival -> first token (s)
        # speculative decoding surface (PR 4)
        self.verify_steps = 0            # verify program launches
        self.verify_tokens = 0           # tokens emitted by verify steps
        self.verify_time = 0.0
        self.spec_rounds = 0             # (sequence, verify) acceptance rounds
        self.draft_proposed = 0          # draft tokens sent to verify
        self.draft_accepted = 0          # draft tokens that survived (hits)
        self.spec_emitted_tokens = 0     # tokens emitted by verify steps
        self.rollback_tokens = 0         # draft tokens rolled back
        self.rollback_pages = 0          # pages released by truncate
        self.spec_disables = 0           # requests whose speculation tripped off
        # request-lifecycle surface (PR 5: the HTTP frontend)
        self.aborts = 0                  # aborted before finishing (any reason)
        self.abort_reasons: dict = {}    # finish_reason -> count
        self.abort_noops = 0             # aborts of finished/unknown rids
        # fault-tolerance surface (PR 7: recovery/quarantine/degradation)
        self.engine_restarts = 0         # supervised engine rebuilds
        self.quarantined = 0             # sequences retired for NaN logits
        self.fault_injections: dict = {} # injected fault kind -> count
        self.degradation_state = 0       # current pressure tier (gauge)
        self.degradation_transitions = 0 # tier changes (counter)
        self.parked_evictions = 0        # pages evicted by tier-3 pressure
        # kernel-autotuning surface (PR 10): per-kernel tuning-cache
        # lookup outcomes at engine build (dict-of-int — aggregate()
        # merges dict values by int addition)
        self.tuning_hits: dict = {}      # kernel -> cache-hit lookups
        self.tuning_misses: dict = {}    # kernel -> default/env fallbacks
        # observability surface (PR 11): exact-count histograms beside
        # the reservoir quantiles, and whole-step wall-clock accounting
        self._ttft_hist = _Hist()
        self._itl_hist = _Hist()
        self._step_hist = _Hist()
        self.engine_steps = 0            # LLMEngine.step launch cycles
        self.step_time = 0.0
        # async-pipeline surface (PR 12): each launch cycle's wall time
        # split into the host dispatch section (pack/stage/enqueue) vs
        # the completion block (waiting on device results) — under
        # overlap the block shrinks toward zero while dispatch stays
        self.dispatch_time = 0.0
        self.block_time = 0.0
        self._dispatch_lat = _Reservoir(r, seed=5)
        self._block_lat = _Reservoir(r, seed=6)
        # device-resident decode-window surface (PR 16): how often the
        # host actually blocked on the device, and how many tokens each
        # block drained — the round-trip amortization the K-step window
        # exists to buy.  decode_window_k is a gauge (the largest window
        # this engine ran); fallbacks count windows the page pool
        # couldn't cover that ran per-step instead
        self.host_round_trips = 0
        self.decode_rounds = 0           # per-row decode positions advanced
        self.decode_window_k = 1
        self.decode_window_fallbacks = 0
        # windows that ran device-resident but at a SHRUNK K' < K
        # because the pool could only pre-reserve K' tokens of slack
        self.decode_window_shrinks = 0
        # weight residency (PR 17): engine-build-time gauges, so they
        # SURVIVE reset like _windows — benches reset between passes
        # without rebuilding the engine, and the pools don't move
        self.weight_dtype = getattr(self, "weight_dtype", "float32")
        self.weight_bytes_resident = getattr(
            self, "weight_bytes_resident", 0)
        self.weight_bytes_resident_per_shard = getattr(
            self, "weight_bytes_resident_per_shard", 0)
        # hierarchical-KV spill tier (PR 20): counters for pages crossing
        # the HBM<->host boundary plus tier gauges the engine pushes at
        # each step-boundary drain.  The gauges SURVIVE reset like the
        # weight gauges — benches reset between passes and the attached
        # tier object (with its cumulative consult counters) doesn't move
        self.kv_pages_spilled = 0        # pages stored into the host tier
        self.kv_pages_restored = 0       # pages restored back into HBM
        self.kv_spill_dropped = 0        # quarantined pages the tier refused
        self.kv_prefetch_hit_pages = 0   # restored pages admission hits used
        self.spill_tier_hits = getattr(self, "spill_tier_hits", 0)
        self.spill_tier_misses = getattr(self, "spill_tier_misses", 0)
        self.host_kv_bytes_resident = getattr(
            self, "host_kv_bytes_resident", 0)
        self.host_kv_bytes_capacity = getattr(
            self, "host_kv_bytes_capacity", 0)
        # SLO-observatory surface (PR 13): queue wait (arrival ->
        # admission) joins the lifetime reservoirs, and an OPT-IN
        # windowed layer (profiler/slo.py) rides beside them — None
        # means every record path below pays one attribute check and
        # never executes a line of slo.py (pinned by tracemalloc test)
        self._queue_wait = _Reservoir(r, seed=7)
        # enablement SURVIVES reset (benches reset between passes, the
        # runner resets nothing but shares stats across rebuilds): the
        # rings are rolling, stale samples age out on their own
        self._windows = getattr(self, "_windows", None)
        self._t_start = time.monotonic() # process-lifetime uptime anchor

    def enable_windows(self, slo=None, *, windows=(10.0, 60.0, 300.0),
                       tracer=None, clock=None):
        """Attach the windowed-telemetry layer (rolling TTFT/ITL/step/
        queue-wait/accept-rate windows + SLO burn-rate state — see
        profiler/slo.py).  Idempotent: the first call builds it from
        ``slo`` (an SLOConfig or None for defaults); later calls return
        the existing layer so engine and frontend can both ask for it."""
        if self._windows is None:
            from .slo import WindowedTelemetry
            kw = {} if clock is None else {"clock": clock}
            self._windows = WindowedTelemetry(slo, windows=windows,
                                              tracer=tracer, **kw)
        return self._windows

    @property
    def windows(self):
        """The windowed-telemetry layer, or None when never enabled."""
        return self._windows

    # -- recording (engine-facing) ------------------------------------------

    def record_prefill(self, duration_s: float, n_prompt_tokens: int,
                       n_seqs: int) -> None:
        self.prefill_steps += 1
        self.prefill_tokens += int(n_prompt_tokens)
        self.prefill_time += float(duration_s)
        # each sequence's first token comes out of the prefill step
        self._token_lat.extend(float(duration_s), int(n_seqs))
        self._itl_hist.add(float(duration_s), int(n_seqs))
        w = self._windows
        if w is not None and n_seqs:
            w.record_itl(float(duration_s), int(n_seqs))

    def record_decode(self, duration_s: float, n_tokens: int,
                      occupancy: float, rounds: int = 1) -> None:
        """``rounds`` is how many per-row decode POSITIONS this launch
        advanced: 1 for a per-step launch (however wide its batch), the
        iteration count for a K-step window drain.  host_round_trips /
        decode_rounds is the sync count on one request's critical path
        — ~1.0 per-step, falling toward 1/K with the window engaged."""
        self.decode_steps += 1
        self.decode_rounds += int(rounds)
        self.decode_tokens += int(n_tokens)
        self.decode_time += float(duration_s)
        self._token_lat.extend(float(duration_s), int(n_tokens))
        self._itl_hist.add(float(duration_s), int(n_tokens))
        self._occupancy.add(float(occupancy))
        w = self._windows
        if w is not None and n_tokens:
            w.record_itl(float(duration_s), int(n_tokens))

    def record_step(self, duration_s: float, dispatch_s: float = 0.0,
                    block_s: float = 0.0) -> None:
        """One launch cycle's wall-clock duration — the whole
        pack/stage/launch/sync section regardless of phase mix.

        ``dispatch_s``/``block_s`` split that duration into the host
        dispatch section (admit/schedule/pack/stage/enqueue, which the
        async engine runs while the previous launch is still on-device)
        and the completion block (materializing device results).  A
        caller that can't attribute the split leaves both at 0; the
        fused total stays authoritative either way."""
        d = float(duration_s)
        self.engine_steps += 1
        self.step_time += d
        self._step_hist.add(d)
        self.dispatch_time += float(dispatch_s)
        self.block_time += float(block_s)
        self._dispatch_lat.add(float(dispatch_s))
        self._block_lat.add(float(block_s))
        w = self._windows
        if w is not None:
            w.record_step(d)

    def record_round_trip(self, n: int = 1) -> None:
        """One host<->device completion block: the host materialized a
        launch's results.  Per-step decode pays one per token; a K-step
        window pays one per K tokens."""
        self.host_round_trips += int(n)

    def set_decode_window(self, k: int) -> None:
        """Largest decode window this engine ran (gauge, monotone)."""
        self.decode_window_k = max(self.decode_window_k, int(k))

    def record_window_fallback(self, n: int = 1) -> None:
        """One eligible decode window that fell back to the per-step
        path because the pool couldn't pre-reserve K tokens of slack."""
        self.decode_window_fallbacks += int(n)

    def record_window_shrink(self, n: int = 1) -> None:
        """One eligible decode window that ran device-resident at a
        shrunk K' < decode_window (the pool covered K' tokens of slack
        but not K) instead of falling back to per-step."""
        self.decode_window_shrinks += int(n)

    def set_weight_residency(self, dtype: str, total_bytes: int,
                             per_shard_bytes: int | None = None) -> None:
        """Engine-build gauges: the weight pools' storage dtype and
        resident bytes (mesh-wide total and the largest single shard —
        equal at tp=1)."""
        self.weight_dtype = str(dtype)
        self.weight_bytes_resident = int(total_bytes)
        self.weight_bytes_resident_per_shard = int(
            total_bytes if per_shard_bytes is None else per_shard_bytes)

    def record_admission(self, n: int = 1) -> None:
        self.admitted += int(n)

    def record_retirement(self, n: int = 1) -> None:
        self.retired += int(n)

    def record_preemption(self, n: int = 1) -> None:
        self.preemptions += int(n)

    def record_abort(self, reason: str = "aborted") -> None:
        """One request retired before finishing (client disconnect,
        deadline, shutdown drain, explicit cancel)."""
        self.aborts += 1
        self.abort_reasons[reason] = self.abort_reasons.get(reason, 0) + 1

    def record_cache_lookup(self, hit_tokens: int, miss_tokens: int) -> None:
        """One admission's prefix-cache match: how many prompt tokens the
        cache already held vs how many must be prefilled."""
        self.cache_hit_tokens += int(hit_tokens)
        self.cache_miss_tokens += int(miss_tokens)

    def record_cow(self, n: int = 1) -> None:
        self.cow_copies += int(n)

    def record_evictions(self, n: int = 1) -> None:
        self.cache_evictions += int(n)

    def record_prefill_queue(self, depth: int) -> None:
        """Requests (running or waiting) with prompt tokens still to
        prefill at this step — the chunked-prefill backlog."""
        self._prefill_queue.add(int(depth))

    def record_ttft(self, duration_s: float) -> None:
        self._ttft.add(float(duration_s))
        self._ttft_hist.add(float(duration_s))
        w = self._windows
        if w is not None:
            w.record_ttft(float(duration_s))

    def record_queue_wait(self, duration_s: float) -> None:
        """Seconds one request sat queued between arrival and engine
        admission — the scheduler-pressure signal the future SLO-aware
        admission predictor consumes."""
        self._queue_wait.add(float(duration_s))
        w = self._windows
        if w is not None:
            w.record_queue_wait(float(duration_s))

    def record_request_latency(self, duration_s: float) -> None:
        """One finished request's arrival-to-last-token latency; feeds
        the windowed slow-request anomaly detector (windowed layer
        only — lifetime latency already decomposes into TTFT + ITL)."""
        w = self._windows
        if w is not None:
            w.record_request(float(duration_s))

    def record_deadline(self, met: bool) -> None:
        """One deadline-bearing request finished: did it beat its
        deadline?  (Windowed layer only; recorded by the runner.)"""
        w = self._windows
        if w is not None:
            w.record_deadline(bool(met))

    def record_finish_quality(self, ok: bool) -> None:
        """One finished request, natural (True) or errored (False) —
        the availability objective's windowed sample."""
        w = self._windows
        if w is not None:
            w.record_finish(bool(ok))

    def record_verify(self, duration_s: float, n_tokens: int,
                      occupancy: float) -> None:
        """One verify-program launch that emitted n_tokens across its
        speculative sequences.  Verify output stays in its OWN channel:
        folding it into decode_tokens/decode_time (as this method once
        did) made the on/off "speedup" ratio compare verify throughput
        against decode throughput of a different token mix — a
        bookkeeping artifact, not a measurement.  Cross-phase
        comparisons use wall-clock emitted tok/s per phase instead.
        The tokens still feed the stream-wide ITL reservoir (they are
        real emitted tokens and each observed this step's latency)."""
        self.verify_steps += 1
        self.verify_time += float(duration_s)
        self.verify_tokens += int(n_tokens)
        self._token_lat.extend(float(duration_s), int(n_tokens))
        self._itl_hist.add(float(duration_s), int(n_tokens))
        self._occupancy.add(float(occupancy))
        w = self._windows
        if w is not None and n_tokens:
            w.record_itl(float(duration_s), int(n_tokens))

    def record_spec(self, *, proposed: int, accepted: int, emitted: int,
                    rollback: int, pages_rolled: int = 0) -> None:
        """One sequence's acceptance round inside a verify step."""
        self.spec_rounds += 1
        self.draft_proposed += int(proposed)
        self.draft_accepted += int(accepted)
        self.spec_emitted_tokens += int(emitted)
        self.rollback_tokens += int(rollback)
        self.rollback_pages += int(pages_rolled)
        w = self._windows
        if w is not None and proposed:
            w.record_accept(int(accepted), int(proposed))

    def record_spec_disable(self, n: int = 1) -> None:
        self.spec_disables += int(n)

    def record_abort_noop(self, n: int = 1) -> None:
        """Abort of an unknown/already-finished request id — benign
        (an abort racing natural retirement), but counted so a frontend
        bug that aborts wildly is visible."""
        self.abort_noops += int(n)

    def record_restart(self, n: int = 1) -> None:
        """One supervised engine rebuild (crash or hung-step watchdog)."""
        self.engine_restarts += int(n)

    def record_quarantine(self, n: int = 1) -> None:
        """One sequence retired with finish_reason='numerical_error'."""
        self.quarantined += int(n)

    def record_fault(self, kind: str, n: int = 1) -> None:
        """One injected fault fired (kind: crash/slow/nan/pool/conn)."""
        self.fault_injections[kind] = \
            self.fault_injections.get(kind, 0) + int(n)

    def set_degradation_state(self, state: int) -> None:
        """Current pressure tier; transitions are counted."""
        state = int(state)
        if state != self.degradation_state:
            self.degradation_transitions += 1
            self.degradation_state = state

    def record_parked_evictions(self, n: int = 1) -> None:
        self.parked_evictions += int(n)

    def record_kv_spill(self, quarantined: int, stored: int) -> None:
        """One step-boundary spill drain: ``quarantined`` pages left the
        HBM pool, ``stored`` of them landed in the host tier (the rest
        were counted drops — tier full of bigger pages, or disabled)."""
        self.kv_pages_spilled += int(stored)
        self.kv_spill_dropped += int(quarantined) - int(stored)

    def record_kv_restore(self, n: int = 1) -> None:
        """Pages restored from the host tier into free HBM blocks and
        re-registered in the prefix cache."""
        self.kv_pages_restored += int(n)

    def record_prefetch_hits(self, n_pages: int = 1) -> None:
        """Restored pages a later admission's prefix-cache hit actually
        used (attributed by chain hash) — the tier's payoff counter."""
        self.kv_prefetch_hit_pages += int(n_pages)

    def set_spill_tier(self, tier_stats: dict) -> None:
        """Absorb the attached HostSpillPool's gauge snapshot (its
        ``stats()`` dict): cumulative consult hits/misses and resident/
        capacity bytes.  Pushed by the engine after every drain."""
        self.spill_tier_hits = int(tier_stats.get("hits", 0))
        self.spill_tier_misses = int(tier_stats.get("misses", 0))
        self.host_kv_bytes_resident = int(
            tier_stats.get("bytes_resident", 0))
        self.host_kv_bytes_capacity = int(
            tier_stats.get("capacity_bytes", 0))

    def record_tuning(self, kernel: str, hit: bool) -> None:
        """One tuning-cache lookup for a kernel's launch geometry (the
        engine resolves each registered kernel once at build)."""
        slot = self.tuning_hits if hit else self.tuning_misses
        slot[kernel] = slot.get(kernel, 0) + 1

    def uptime_seconds(self) -> float:
        """Seconds since these stats were created/reset.  The runner
        carries one ServingStats across engine rebuilds, so this is the
        SERVICE uptime, not the current engine's."""
        return time.monotonic() - self._t_start

    # -- derived metrics ----------------------------------------------------

    def decode_tokens_per_s(self) -> float:
        return self.decode_tokens / self.decode_time if self.decode_time \
            else 0.0

    def verify_tokens_per_s(self) -> float:
        return self.verify_tokens / self.verify_time if self.verify_time \
            else 0.0

    def prefill_tokens_per_s(self) -> float:
        return self.prefill_tokens / self.prefill_time \
            if self.prefill_time else 0.0

    def emitted_tokens_per_s(self) -> float:
        """Wall-clock emitted throughput across decode AND verify — the
        honest cross-phase number for spec on/off comparisons."""
        t = self.decode_time + self.verify_time
        return (self.decode_tokens + self.verify_tokens) / t if t else 0.0

    def tokens_per_launch(self) -> float:
        """Emitted tokens (decode + verify) per host round-trip — 1.0
        for the per-step engine, approaching K with the decode window
        engaged (prefill round-trips emit via TTFT, not here, so a
        prefill-heavy stream honestly drags this below 1)."""
        return (self.decode_tokens + self.verify_tokens) \
            / self.host_round_trips if self.host_round_trips else 0.0

    def token_latency_ms(self, q: float) -> float:
        return 1e3 * self._token_lat.percentile(q)

    def mean_occupancy(self) -> float:
        return self._occupancy.mean()

    def prefix_hit_rate(self) -> float:
        total = self.cache_hit_tokens + self.cache_miss_tokens
        return self.cache_hit_tokens / total if total else 0.0

    def ttft_ms(self, q: float) -> float:
        return 1e3 * self._ttft.percentile(q)

    def accept_rate(self) -> float:
        return self.draft_accepted / self.draft_proposed \
            if self.draft_proposed else 0.0

    def spill_tier_hit_rate(self) -> float:
        """Fraction of spill-tier consults (admission chain walks +
        router prefetch hints) that found a resident page."""
        total = self.spill_tier_hits + self.spill_tier_misses
        return self.spill_tier_hits / total if total else 0.0

    def snapshot(self, include_samples: bool = False) -> dict:
        """Point-in-time view of every counter and on-demand percentile.
        The ONE read surface: the frontend's ``/metrics`` endpoint and
        serve_bench both render this dict.  Safe to call from a thread
        other than the recording one (reservoirs lock internally;
        counters are plain ints read atomically under the GIL).

        ``include_samples=True`` additionally attaches the raw latency
        reservoir samples under ``"_samples"`` so ``aggregate()`` can
        recompute fleet percentiles over the pooled union instead of
        falling back to the worst replica's quantile.  The key is
        underscore-prefixed and stripped by the metrics renderer."""
        out = {
            "prefill_steps": self.prefill_steps,
            "prefill_tokens": self.prefill_tokens,
            "decode_steps": self.decode_steps,
            "decode_tokens": self.decode_tokens,
            "decode_tokens_per_s": round(self.decode_tokens_per_s(), 2),
            "prefill_tokens_per_s": round(self.prefill_tokens_per_s(), 2),
            "verify_tokens": self.verify_tokens,
            "verify_tokens_per_s": round(self.verify_tokens_per_s(), 2),
            "emitted_tokens_per_s": round(self.emitted_tokens_per_s(), 2),
            "p50_token_ms": round(self.token_latency_ms(50), 3),
            "p99_token_ms": round(self.token_latency_ms(99), 3),
            "itl_p50_ms": round(self.token_latency_ms(50), 3),
            "itl_p99_ms": round(self.token_latency_ms(99), 3),
            "mean_batch_occupancy": round(self.mean_occupancy(), 4),
            "admitted": self.admitted,
            "retired": self.retired,
            "preemptions": self.preemptions,
            "aborts": self.aborts,
            "abort_reasons": dict(self.abort_reasons),
            "cache_hit_tokens": self.cache_hit_tokens,
            "cache_miss_tokens": self.cache_miss_tokens,
            "prefix_hit_rate": round(self.prefix_hit_rate(), 4),
            "prefill_tokens_saved": self.cache_hit_tokens,
            "cow_copies": self.cow_copies,
            "cache_evictions": self.cache_evictions,
            "mean_prefill_queue_depth": round(self._prefill_queue.mean(), 3),
            "max_prefill_queue_depth": int(self._prefill_queue.vmax),
            "ttft_p50_ms": round(self.ttft_ms(50), 3),
            "ttft_p99_ms": round(self.ttft_ms(99), 3),
            "verify_steps": self.verify_steps,
            "spec_rounds": self.spec_rounds,
            "draft_proposed": self.draft_proposed,
            "draft_accepted": self.draft_accepted,
            "accept_rate": round(self.accept_rate(), 4),
            "spec_emitted_tokens": self.spec_emitted_tokens,
            "rollback_tokens": self.rollback_tokens,
            "rollback_pages": self.rollback_pages,
            "spec_disables": self.spec_disables,
            "abort_noops": self.abort_noops,
            "engine_restarts": self.engine_restarts,
            "uptime_seconds": round(self.uptime_seconds(), 3),
            "quarantined": self.quarantined,
            "fault_injections": dict(self.fault_injections),
            "faults_injected_total": sum(self.fault_injections.values()),
            "degradation_state": self.degradation_state,
            "degradation_transitions": self.degradation_transitions,
            "parked_evictions": self.parked_evictions,
            "tuning_cache_hits": dict(self.tuning_hits),
            "tuning_cache_misses": dict(self.tuning_misses),
            "host_round_trips": self.host_round_trips,
            "decode_rounds": self.decode_rounds,
            "tokens_per_launch": round(self.tokens_per_launch(), 3),
            "decode_window_k": self.decode_window_k,
            "decode_window_fallbacks": self.decode_window_fallbacks,
            "decode_window_shrinks": self.decode_window_shrinks,
            "weight_dtype": self.weight_dtype,
            "weight_bytes_resident": self.weight_bytes_resident,
            "weight_bytes_resident_per_shard":
                self.weight_bytes_resident_per_shard,
            "kv_pages_spilled": self.kv_pages_spilled,
            "kv_pages_restored": self.kv_pages_restored,
            "kv_spill_dropped": self.kv_spill_dropped,
            "kv_prefetch_hit_pages": self.kv_prefetch_hit_pages,
            "spill_tier_hits": self.spill_tier_hits,
            "spill_tier_misses": self.spill_tier_misses,
            "spill_tier_hit_rate": round(self.spill_tier_hit_rate(), 4),
            "host_kv_bytes_resident": self.host_kv_bytes_resident,
            "host_kv_bytes_capacity": self.host_kv_bytes_capacity,
            "engine_steps": self.engine_steps,
            "step_time_s": round(self.step_time, 6),
            "dispatch_time_s": round(self.dispatch_time, 6),
            "block_time_s": round(self.block_time, 6),
            "dispatch_ms_p50": round(1e3 * self._dispatch_lat.percentile(50), 3),
            "dispatch_ms_p99": round(1e3 * self._dispatch_lat.percentile(99), 3),
            "block_ms_p50": round(1e3 * self._block_lat.percentile(50), 3),
            "block_ms_p99": round(1e3 * self._block_lat.percentile(99), 3),
            "ttft_hist_buckets": self._ttft_hist.buckets(),
            "ttft_hist_sum": self._ttft_hist.total,
            "ttft_hist_count": self._ttft_hist.count,
            "itl_hist_buckets": self._itl_hist.buckets(),
            "itl_hist_sum": self._itl_hist.total,
            "itl_hist_count": self._itl_hist.count,
            "step_hist_buckets": self._step_hist.buckets(),
            "step_hist_sum": self._step_hist.total,
            "step_hist_count": self._step_hist.count,
            "queue_wait_p50_ms": round(
                1e3 * self._queue_wait.percentile(50), 3),
            "queue_wait_p99_ms": round(
                1e3 * self._queue_wait.percentile(99), 3),
        }
        if self._windows is not None:
            out.update(self._windows.snapshot_keys())
        if include_samples:
            out["_samples"] = {"token_lat": self._token_lat.samples(),
                               "ttft": self._ttft.samples()}
        return out

    # summary() predates snapshot() and is the name the engine/benches
    # grew up with; both return the same dict
    summary = snapshot

    # ------------------------------------------------------------------
    # fleet aggregation
    # ------------------------------------------------------------------

    # snapshot keys that are NOT plain summable counters, by how a
    # D-replica fleet combines them:
    #   _RATE     recomputed from the summed numerator/denominator —
    #             summing or averaging ratios of unequal denominators
    #             would misweight replicas
    #   _THROUGH  summed: replicas run in parallel, fleet tokens/s is
    #             the sum of per-replica tokens/s
    #   _MAX      worst replica wins — the FALLBACK for latency
    #             percentiles when snapshots carry no reservoir samples
    #             (when every snapshot was taken with
    #             include_samples=True the percentiles are instead
    #             recomputed over the pooled sample union — honest
    #             fleet quantiles, not a max-of-quantiles bound);
    #             degradation_state and uptime always describe the
    #             worst/oldest member
    #   _MEAN     unweighted mean across replicas (occupancy/queue depth
    #             are already per-engine means)
    _RATE = ("prefix_hit_rate", "accept_rate", "tokens_per_launch",
             "spill_tier_hit_rate")
    _THROUGH = ("decode_tokens_per_s", "prefill_tokens_per_s",
                "verify_tokens_per_s", "emitted_tokens_per_s")
    _MAX = ("p50_token_ms", "p99_token_ms", "itl_p50_ms", "itl_p99_ms",
            "ttft_p50_ms", "ttft_p99_ms", "max_prefill_queue_depth",
            "uptime_seconds", "degradation_state", "decode_window_k",
            "dispatch_ms_p50", "dispatch_ms_p99",
            "block_ms_p50", "block_ms_p99",
            "queue_wait_p50_ms", "queue_wait_p99_ms",
            "weight_bytes_resident_per_shard")
    _MEAN = ("mean_batch_occupancy", "mean_prefill_queue_depth")
    # windowed-telemetry keys (present only when enable_windows() ran)
    # are pooled structurally after the generic pass: bucket counts sum
    # per window index across replicas (identical ladders), windowed
    # percentiles and burn rates recompute from the POOLED distribution,
    # and the fleet SLO state is the worst replica's (a page anywhere
    # pages the fleet)
    _WINDOWED = ("windows", "slo", "slo_state", "slo_state_name",
                 "ttft_p95_w60s", "itl_p99_w60s", "queue_wait_p95_w60s",
                 "anomalies_detected", "anomalies_captured",
                 "anomaly_spool_dropped")

    @staticmethod
    def aggregate(snapshots) -> dict:
        """Combine per-replica ``snapshot()`` dicts into one fleet view
        (the dict a replicated frontend's ``/metrics`` renders).  Plain
        counters sum; see the class-level key tables for everything
        else.  A single snapshot passes through semantically unchanged
        (max == mean == sum-of-one)."""
        snaps = list(snapshots)
        if not snaps:
            raise ValueError("aggregate() needs at least one snapshot")
        out: dict = {}
        for key in snaps[0]:
            if key == "_samples" or key in ServingStats._WINDOWED:
                continue                         # pooled below, never summed
            vals = [s[key] for s in snaps]
            if isinstance(vals[0], dict):        # abort_reasons, fault_injections
                merged: dict = {}
                for v in vals:
                    for k, n in v.items():
                        merged[k] = merged.get(k, 0) + n
                out[key] = merged
            elif isinstance(vals[0], str):       # weight_dtype, ...
                out[key] = vals[0] \
                    if all(v == vals[0] for v in vals) else "mixed"
            elif key in ServingStats._RATE:
                pass                             # recomputed below
            elif key in ServingStats._THROUGH:
                out[key] = round(sum(vals), 2)
            elif key in ServingStats._MAX:
                out[key] = max(vals)
            elif key in ServingStats._MEAN:
                out[key] = round(sum(vals) / len(vals), 4)
            else:
                out[key] = sum(vals)
        hit, miss = out["cache_hit_tokens"], out["cache_miss_tokens"]
        out["prefix_hit_rate"] = round(hit / (hit + miss), 4) \
            if hit + miss else 0.0
        out["accept_rate"] = round(
            out["draft_accepted"] / out["draft_proposed"], 4) \
            if out["draft_proposed"] else 0.0
        trips = out.get("host_round_trips", 0)
        out["tokens_per_launch"] = round(
            (out["decode_tokens"] + out["verify_tokens"]) / trips, 3) \
            if trips else 0.0
        consults = out.get("spill_tier_hits", 0) \
            + out.get("spill_tier_misses", 0)
        out["spill_tier_hit_rate"] = round(
            out["spill_tier_hits"] / consults, 4) if consults else 0.0
        if all("_samples" in s for s in snaps):
            # honest fleet quantiles: pool every replica's reservoir
            # sample and recompute, replacing the max-of-quantiles
            # fallback written by the _MAX pass above
            tok = sorted(v for s in snaps
                         for v in s["_samples"]["token_lat"])
            ttft = sorted(v for s in snaps for v in s["_samples"]["ttft"])
            for q in (50, 99):
                out[f"p{q}_token_ms"] = round(
                    1e3 * _percentile(tok, q), 3)
                out[f"itl_p{q}_ms"] = out[f"p{q}_token_ms"]
                out[f"ttft_p{q}_ms"] = round(
                    1e3 * _percentile(ttft, q), 3)
        windowed = [s for s in snaps if "windows" in s]
        if windowed:
            from .slo import (SLO_STATE_NAMES, aggregate_windows,
                              evaluate_slo)
            ws = aggregate_windows([s["windows"] for s in windowed])
            out["windows"] = ws
            ev = evaluate_slo(windowed[0]["slo"]["config"], ws)
            # worst replica wins over the fleet-level evaluation: one
            # paging replica must not be averaged away by healthy peers
            state = max([ev["state"]]
                        + [s.get("slo_state", 0) for s in windowed])
            ev["state"] = state
            ev["state_name"] = SLO_STATE_NAMES[state]
            out["slo"] = ev
            out["slo_state"] = state
            out["slo_state_name"] = SLO_STATE_NAMES[state]
            mid = sorted((k for k in ws if k != "bounds"),
                         key=lambda s: float(s[:-1]))
            mid = mid[min(1, len(mid) - 1)] if mid else None
            if mid is not None:
                out["ttft_p95_w60s"] = ws[mid]["ttft"]["p95_ms"]
                out["itl_p99_w60s"] = ws[mid]["itl"]["p99_ms"]
                out["queue_wait_p95_w60s"] = \
                    ws[mid]["queue_wait"]["p95_ms"]
            for key in ("anomalies_detected", "anomalies_captured",
                        "anomaly_spool_dropped"):
                out[key] = sum(s.get(key, 0) for s in windowed)
        out["replicas"] = len(snaps)
        return out
