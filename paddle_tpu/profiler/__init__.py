"""Profiler.

Parity with /root/reference/python/paddle/profiler/profiler.py (Profiler
:358, scheduler states :89, export_chrome_tracing :227) and
profiler_statistic.py, re-based on TPU tooling: host annotations are
recorded by a lightweight in-process tracer (and mirrored into
jax.profiler.TraceAnnotation so they appear in XPlane device traces), while
device-side timelines come from jax.profiler.start_trace/stop_trace
(TensorBoard-compatible) — replacing the reference's CUPTI CudaTracer.
Chrome-trace JSON export keeps the reference's output contract.
"""
from .profiler import (  # noqa: F401
    Profiler, ProfilerState, ProfilerTarget, RecordEvent,
    export_chrome_tracing, load_profiler_result, make_scheduler,
)
from .serving import ServingStats  # noqa: F401
from .slo import (  # noqa: F401
    AnomalyDetector, AnomalySpool, SLOConfig, SLOMonitor,
    WindowedTelemetry,
)
from .timer import benchmark  # noqa: F401
from .trace import Tracer  # noqa: F401

__all__ = [
    "Profiler", "ProfilerState", "ProfilerTarget", "RecordEvent",
    "make_scheduler", "export_chrome_tracing", "load_profiler_result",
    "benchmark", "ServingStats", "Tracer",
    "SLOConfig", "SLOMonitor", "WindowedTelemetry", "AnomalyDetector",
    "AnomalySpool",
]


class SortedKeys:
    """Summary-table sort keys (reference profiler.py SortedKeys enum)."""
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


class SummaryView:
    """Summary view selector (reference profiler.py SummaryView enum)."""
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


def export_protobuf(dir_name=None, worker_name=None):
    """Profiler on_trace_ready exporting a serialized trace (reference
    profiler.py:280 export_protobuf).  This build's native trace format is
    the chrome JSON; the protobuf exporter writes the same event stream as
    a pickled payload (protobuf compilation is a build-time step this
    environment doesn't carry) under .pb naming for tooling pick-up."""
    import os
    import pickle
    import socket
    import time as _time

    def handle(prof):
        nonlocal dir_name
        d = dir_name or "profiler_log"
        os.makedirs(d, exist_ok=True)
        w = worker_name or f"host_{socket.gethostname()}"
        path = os.path.join(
            d, f"{w}_time_{_time.strftime('%Y_%m_%d_%H_%M_%S')}.paddle_trace.pb")
        events = getattr(prof, "_events", None) or getattr(
            prof, "events", lambda: [])()
        with open(path, "wb") as f:
            pickle.dump({"format": "paddle_tpu-trace-v1",
                         "events": events}, f)
        return path

    return handle


__all__ += ["SortedKeys", "SummaryView", "export_protobuf"]
