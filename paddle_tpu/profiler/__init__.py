"""Profiler.

Parity with /root/reference/python/paddle/profiler/profiler.py (Profiler
:358, scheduler states :89, export_chrome_tracing :227) and
profiler_statistic.py, re-based on TPU tooling: host annotations are
recorded by a lightweight in-process tracer (and mirrored into
jax.profiler.TraceAnnotation so they appear in XPlane device traces), while
device-side timelines come from jax.profiler.start_trace/stop_trace
(TensorBoard-compatible) — replacing the reference's CUPTI CudaTracer.
Chrome-trace JSON export keeps the reference's output contract.
"""
from .profiler import (  # noqa: F401
    Profiler, ProfilerState, ProfilerTarget, RecordEvent,
    export_chrome_tracing, load_profiler_result, make_scheduler,
)
from .timer import benchmark  # noqa: F401

__all__ = [
    "Profiler", "ProfilerState", "ProfilerTarget", "RecordEvent",
    "make_scheduler", "export_chrome_tracing", "load_profiler_result",
    "benchmark",
]
